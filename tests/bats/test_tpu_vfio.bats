#!/usr/bin/env bats
# VFIO passthrough (SURVEY §2.1 / reference vfio-device.go): a claim on the
# vfio alias rebinds the chip's PCI function to vfio-pci (sysfs
# driver_override), injects the /dev/vfio group nodes, withholds the full
# chip while the alias is held, and reverts on unprepare.

load helpers.sh

setup_file() {
  cluster_up --nodes 1 --chips-per-node 2 --vfio \
    --feature-gates PassthroughSupport=true
}

teardown_file() {
  cluster_down
}

@test "vfio aliases advertised alongside full chips" {
  run kubectl get resourceslices -o json
  [[ "$output" == *'"tpu-vfio-0"'* ]]
  [[ "$output" == *'"tpu-0"'* ]]
}

@test "a vfio claim rebinds the device and injects the group nodes" {
  cat > "$TPUDRA_STATE/vfio.yaml" <<'EOF'
apiVersion: resource.k8s.io/v1
kind: ResourceClaimTemplate
metadata:
  namespace: default
  name: vfio-chip
spec:
  spec:
    devices:
      requests:
        - name: dev
          exactly:
            deviceClassName: tpu-vfio.google.com
      config:
        - opaque:
            driver: tpu.google.com
            parameters:
              apiVersion: resource.tpu.google.com/v1beta1
              kind: VfioDeviceConfig
---
apiVersion: v1
kind: Pod
metadata:
  namespace: default
  name: vfio-pod
spec:
  restartPolicy: Never
  containers:
    - name: ctr
      image: tpudra-workload:latest
      command: ["python", "-c"]
      args:
        - |
          import os, time
          nodes = os.environ.get("SIM_CDI_DEVICE_NODES", "")
          assert "/dev/vfio/" in nodes, nodes
          print("vfio nodes:", nodes)
          time.sleep(600)
      resources:
        claims: [{name: dev}]
  resourceClaims:
    - name: dev
      resourceClaimTemplateName: vfio-chip
EOF
  kubectl apply -f "$TPUDRA_STATE/vfio.yaml"
  wait_until 90 sh -c "kubectl get pod vfio-pod -o 'jsonpath={.status.phase}' | grep -q Running"
  # The sysfs rebind actually happened.
  chip_dir=$(ls -d "$TPUDRA_STATE"/node-0/sys/bus/pci/devices/* | head -1)
  grep -q vfio-pci "$chip_dir/driver_override"
}

@test "the sibling full chip is withheld while the vfio alias is held" {
  # tpu-0's silicon is claimed through its vfio alias: only tpu-1 remains.
  cat > "$TPUDRA_STATE/two-chips.yaml" <<'EOF'
apiVersion: resource.k8s.io/v1
kind: ResourceClaimTemplate
metadata:
  namespace: default
  name: two-chips
spec:
  spec:
    devices:
      requests:
        - name: tpu
          exactly:
            deviceClassName: tpu.google.com
            count: 2
---
apiVersion: v1
kind: Pod
metadata:
  namespace: default
  name: two-chips-pod
spec:
  restartPolicy: Never
  containers:
    - name: ctr
      image: tpudra-workload:latest
      command: ["python", "-c", "print('ran')"]
      resources:
        claims: [{name: tpu}]
  resourceClaims:
    - name: tpu
      resourceClaimTemplateName: two-chips
EOF
  kubectl apply -f "$TPUDRA_STATE/two-chips.yaml"
  sleep 3
  run kubectl get pod two-chips-pod -o 'jsonpath={.spec.nodeName}'
  [ -z "$output" ]
}

@test "unprepare reverts the driver_override and frees the silicon" {
  kubectl delete pod vfio-pod
  # The pod object vanishes synchronously; the unprepare that reverts the
  # override runs on the sim kubelet's next reconcile tick — poll for it.
  chip_dir=$(ls -d "$TPUDRA_STATE"/node-0/sys/bus/pci/devices/* | head -1)
  wait_until 60 sh -c "! grep -q vfio-pci '$chip_dir/driver_override'"
  # With the alias released, the 2-chip claim can now bind.
  wait_until 90 sh -c "kubectl get pod two-chips-pod -o 'jsonpath={.status.phase}' | grep -q Succeeded"
  kubectl delete pod two-chips-pod
}

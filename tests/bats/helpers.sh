#!/usr/bin/env bash
# Shared helpers for the bats e2e suite (the analog of the reference's
# tests/bats/helpers.sh).  Each test file calls `cluster_up [flags]` from
# setup_file and `cluster_down` from teardown_file; the hermetic cluster is
# per-file, like the reference's per-file helm install (helpers.sh:42-60).
#
# TPUDRA_BATS_KEEP=1 keeps the state dir on teardown for debugging.

BATS_DIR="$(cd "$(dirname "${BASH_SOURCE[0]}")" && pwd)"
REPO="$(cd "$BATS_DIR/../.." && pwd)"
export PATH="$BATS_DIR/bin:$PATH"

cluster_up() {
  TPUDRA_STATE="$(mktemp -d /tmp/tpubats-XXXXXX)"
  export TPUDRA_STATE
  python3 "$BATS_DIR/clusterctl.py" up --state "$TPUDRA_STATE" "$@" >/dev/null
  # shellcheck disable=SC1091
  source "$TPUDRA_STATE/env.sh"
}

cluster_down() {
  [ -n "${TPUDRA_STATE:-}" ] || return 0
  python3 "$BATS_DIR/clusterctl.py" down --state "$TPUDRA_STATE" || true
  if [ -z "${TPUDRA_BATS_KEEP:-}" ]; then
    rm -rf "$TPUDRA_STATE"
  else
    echo "# state kept at $TPUDRA_STATE" >&2
  fi
}

# wait_until <timeout-s> <cmd...> — poll until the command succeeds.
wait_until() {
  local timeout="$1"; shift
  local deadline=$((SECONDS + timeout))
  while [ "$SECONDS" -lt "$deadline" ]; do
    if "$@" >/dev/null 2>&1; then return 0; fi
    sleep 0.3
  done
  echo "wait_until: timed out: $*" >&2
  return 1
}

# health_port <node> — the node's plugin healthcheck port from env.sh.
health_port() {
  local v="${TPUDRA_HEALTH_PORTS#*$1=}"
  echo "${v%% *}"
}

# prepare_count <node> — current value of the prepare histogram counter.
prepare_count() {
  curl -fsS "http://127.0.0.1:$(health_port "$1")/metrics" \
    | grep 'tpudra_prepare_seconds_count' | grep -o '[0-9.]*$' | head -1
}

# pod_phase <name> [ns]
pod_phase() {
  kubectl get pod "$1" -n "${2:-default}" -o 'jsonpath={.status.phase}' 2>/dev/null
}

# pod_succeeded <name> [ns] — true when phase is Succeeded.
pod_succeeded() {
  [ "$(pod_phase "$1" "${2:-default}")" = "Succeeded" ]
}

# pod_log_has <pod> <pattern> [ns]
pod_log_has() {
  kubectl logs "$1" -n "${3:-default}" | grep -q "$2"
}

# apply_spec <file relative to demo/specs or absolute>
apply_spec() {
  local f="$1"
  [ -f "$f" ] || f="$REPO/demo/specs/$1"
  kubectl apply -f "$f"
}

# plugin_log <what> — driver process logs from the state dir (the analog of
# the reference's failure hooks dumping plugin logs, test_gpu_basic.bats:18).
plugin_log() {
  cat "$TPUDRA_STATE/logs/$1.log" 2>/dev/null || true
}

dump_cluster_state() {
  echo "--- pods:"; kubectl get pods -A || true
  echo "--- claims:"; kubectl get resourceclaims -A -o name || true
  echo "--- slices:"; kubectl get resourceslices -o name || true
  for f in "${TPUDRA_STATE:-}"/logs/*.log; do
    [ -f "$f" ] || continue  # unexpanded glob: no logs (partial cluster_up)
    echo "--- ${f##*/} (tail):"; tail -20 "$f" || true
  done
}

#!/usr/bin/env bats
# ComputeDomain channel injection (the reference's
# test_cd_imex_chan_inject.bats analog): a CD pulls its daemon onto the
# workload's node, the real compute-domain-daemon + tpu-slicewatchd form
# the domain, and the gated workload pod starts with its channel injected.

load helpers.sh

setup_file() {
  cluster_up --nodes 1 --cd
}

teardown_file() {
  cluster_down
}

@test "controller materializes RCTs for the ComputeDomain" {
  apply_spec domain/channel-injection.yaml
  # Workload RCT appears in the user namespace, daemon RCT in the driver's.
  wait_until 60 kubectl get resourceclaimtemplates chan-single-rct -n tpu-domain-demo -o name
  wait_until 60 sh -c "kubectl get resourceclaimtemplates -n $TPUDRA_NAMESPACE -o name | grep -q ."
}

@test "workload pod is gated until the domain forms, then runs" {
  # The channel claim's prepare blocks (retryable error) until the CD is
  # Ready; the daemon DS is pulled onto the node by the claim itself.
  wait_until 60 sh -c "kubectl get daemonsets -n $TPUDRA_NAMESPACE -o name | grep -q computedomain-daemon"
  wait_until 180 pod_succeeded chan-single-pod tpu-domain-demo
  run kubectl logs chan-single-pod -n tpu-domain-demo
  [[ "$output" == *"channels ['0']"* ]] || [[ "$output" == *"channels"* ]]
}

@test "CD status is Ready with the node listed" {
  run kubectl get computedomains chan-single -n tpu-domain-demo -o 'jsonpath={.status.status}'
  [ "$output" = "Ready" ]
  run kubectl get computedomains chan-single -n tpu-domain-demo -o 'jsonpath={.status.nodes[*].name}'
  [[ "$output" == *"node-0"* ]]
}

@test "clique CR carries a Ready daemon entry" {
  run kubectl get computedomaincliques -n "$TPUDRA_NAMESPACE" -o json
  [ "$status" -eq 0 ]
  [[ "$output" == *'"status": "Ready"'* ]]
}

@test "deleting the CD tears down DS, RCTs, and node labels" {
  kubectl delete computedomains chan-single -n tpu-domain-demo
  wait_until 90 sh -c "! kubectl get computedomains -n tpu-domain-demo -o name | grep -q chan"
  wait_until 90 sh -c "! kubectl get daemonsets -n $TPUDRA_NAMESPACE -o name | grep -q computedomain-daemon"
  wait_until 90 sh -c "! kubectl get resourceclaimtemplates chan-single-rct -n tpu-domain-demo -o name 2>/dev/null | grep -q chan"
  run kubectl get nodes node-0 -o 'jsonpath={.metadata.labels}'
  ! echo "$output" | grep -q computeDomain
}

#!/usr/bin/env bats
# Cross-host collective through a claimed ComputeDomain (the reference's
# NCCL send/recv/broadcast assertion, test_cd_mnnvl_workload.bats:18-35):
# two worker pods on the domain's two nodes join jax.distributed via the
# grant env (TPUDRA_NUM_HOSTS / HOST_INDEX, coordinator) and run a real
# cross-process XLA reduction.

load helpers.sh

setup_file() {
  cluster_up --nodes 2 --cd
  # TOCTOU note: the port is released here and rebound by worker-0's jax
  # coordinator once the domain forms; bats files run serially, so the
  # window is effectively private to this file.
  COORD_PORT=$(python3 -c "import socket; s=socket.socket(); s.bind(('127.0.0.1',0)); print(s.getsockname()[1]); s.close()")
  export COORD_PORT
}

teardown_file() {
  cluster_down
}

@test "two pods psum across the domain via DCN rendezvous" {
  cat > "$TPUDRA_STATE/coll.yaml" <<EOF
apiVersion: v1
kind: Namespace
metadata:
  name: coll
---
apiVersion: resource.tpu.google.com/v1beta1
kind: ComputeDomain
metadata:
  namespace: coll
  name: coll
spec:
  numNodes: 2
  channel:
    resourceClaimTemplate:
      name: coll-rct
    allocationMode: Single
EOF
  for n in 0 1; do
    cat >> "$TPUDRA_STATE/coll.yaml" <<EOF
---
apiVersion: v1
kind: Pod
metadata:
  namespace: coll
  name: worker-$n
spec:
  restartPolicy: Never
  nodeSelector:
    kubernetes.io/hostname: node-$n
  containers:
    - name: ctr
      image: tpudra-workload:latest
      env:
        # Sim-only override: both "hosts" are one machine here, so host 0
        # and the daemon's coordinator proxy would contend for one port —
        # the grant's stable-DNS coordinator is swapped for loopback.  On
        # a real cluster this var is absent: host 0 binds its own pod IP
        # and registers it in TPUDRA_CD_DIR, and the index-0 daemon's
        # proxy forwards the stable name to it (the full path is covered
        # hermetically by tests/test_coordproxy.py).
        - name: TPUDRA_SIM_COORDINATOR
          value: "127.0.0.1:$COORD_PORT"
      command: ["python", "-c"]
      args:
        - |
          import os
          import jax
          jax.config.update("jax_platforms", "cpu")
          from tpudra.workload.envspec import ClaimEnv
          env = ClaimEnv.from_environ()
          assert env.num_hosts == 2, env.num_hosts
          assert env.coordinator, "grant injected no coordinator"
          env.coordinator = os.environ.get("TPUDRA_SIM_COORDINATOR") or env.coordinator
          env.initialize_distributed()
          assert jax.process_count() == 2
          import numpy as np
          import jax.numpy as jnp
          from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
          from jax.experimental import multihost_utils
          mesh = Mesh(np.asarray(jax.devices()), ("dp",))
          local = jnp.ones((1, 4), jnp.float32) * (env.host_index + 1)
          garr = multihost_utils.host_local_array_to_global_array(local, mesh, P("dp", None))
          total = jax.jit(lambda a: a.sum(), out_shardings=NamedSharding(mesh, P()))(garr)
          val = float(total.addressable_data(0))
          assert val == 12.0, val  # (1 + 2) * 4 across both hosts
          print("RESULT psum:", val, "host", env.host_index)
      resources:
        claims:
          - name: channel
  resourceClaims:
    - name: channel
      resourceClaimTemplateName: coll-rct
EOF
  done
  kubectl apply -f "$TPUDRA_STATE/coll.yaml"
  wait_until 300 pod_succeeded worker-0 coll
  wait_until 300 pod_succeeded worker-1 coll
  run kubectl logs worker-0 -n coll
  [[ "$output" == *"RESULT psum: 12.0 host 0"* ]]
  run kubectl logs worker-1 -n coll
  [[ "$output" == *"RESULT psum: 12.0 host 1"* ]]
}

@test "teardown" {
  kubectl delete pod worker-0 worker-1 -n coll
  kubectl delete computedomains coll -n coll
  wait_until 120 sh -c "! kubectl get computedomains -n coll -o name | grep -q coll"
}

#!/usr/bin/env bats
# Cross-host collective through a claimed ComputeDomain (the reference's
# NCCL send/recv/broadcast assertion, test_cd_mnnvl_workload.bats:18-35):
# two worker pods on the domain's two nodes join jax.distributed via the
# grant env (TPUDRA_NUM_HOSTS / HOST_INDEX, coordinator) and run a real
# cross-process XLA reduction.

load helpers.sh

setup_file() {
  cluster_up --nodes 2 --cd
  # Host-0's coordinator bind port: from clusterctl's single free-port
  # batch, so it cannot collide with the daemon's proxy port (a separate
  # ephemeral pick here could land on the same number).
  COORD_PORT="$TPUDRA_SCRATCH_PORT"
  export COORD_PORT
}

teardown_file() {
  cluster_down
}

@test "two pods psum across the domain via DCN rendezvous" {
  # Worker 0 (host 0) binds + registers COORD_PORT; worker 1 dials the
  # node-0 daemon's REAL coordinator proxy (TPUDRA_COORD_PROXY_PORT from
  # clusterctl), which forwards to the registered endpoint — the whole
  # production relay, minus only the DNS name (both "hosts" are this
  # machine, so the stable name is swapped for loopback).
  cat > "$TPUDRA_STATE/coll.yaml" <<EOF
apiVersion: v1
kind: Namespace
metadata:
  name: coll
---
apiVersion: resource.tpu.google.com/v1beta1
kind: ComputeDomain
metadata:
  namespace: coll
  name: coll
spec:
  numNodes: 2
  channel:
    resourceClaimTemplate:
      name: coll-rct
    allocationMode: Single
EOF
  for n in 0 1; do
    if [ "$n" = 0 ]; then
      # Host 0 parses this port, binds it locally, and registers it in
      # the mounted domain dir (TPUDRA_CD_DIR).
      SIM_COORD="127.0.0.1:$COORD_PORT"
    else
      # Peers go THROUGH the daemon's proxy.
      SIM_COORD="127.0.0.1:$TPUDRA_COORD_PROXY_PORT"
    fi
    cat >> "$TPUDRA_STATE/coll.yaml" <<EOF
---
apiVersion: v1
kind: Pod
metadata:
  namespace: coll
  name: worker-$n
spec:
  restartPolicy: Never
  hostNetwork: true  # multi-host channel contract (test_cd_hostnet.bats)
  nodeSelector:
    kubernetes.io/hostname: node-$n
  containers:
    - name: ctr
      image: tpudra-workload:latest
      env:
        # Sim-only override of the ADDRESS only (the stable DNS name does
        # not resolve on one machine); the relay itself is real — worker 1
        # reaches worker 0 through the node-0 daemon's coordinator proxy.
        - name: TPUDRA_SIM_COORDINATOR
          value: "$SIM_COORD"
      command: ["python", "-c"]
      args:
        - |
          import os
          # The libtpu worker-bootstrap contract must be in the CONTAINER
          # env (not just parseable): libtpu reads the real process env, so
          # the CDI grant has to have injected every var before jax loads.
          assert os.environ["TPU_WORKER_ID"] == os.environ["TPUDRA_HOST_INDEX"]
          assert len(os.environ["TPU_WORKER_HOSTNAMES"].split(",")) == 2
          assert os.environ["TPU_SKIP_MDS_QUERY"] == "true"
          assert os.environ["TPU_HOST_BOUNDS"], "no host bounds injected"
          assert os.environ["TPU_CHIPS_PER_HOST_BOUNDS"], "no chip bounds"
          # Slice geometry rides the grant (cdplugin/libtpuenv.slice_env):
          # each rank learns its mesh position from the claim alone.
          mesh = [int(v) for v in os.environ["TPUDRA_MESH_SHAPE"].split(",")]
          coords = [int(v) for v in os.environ["TPUDRA_HOST_COORDS"].split(",")]
          assert len(mesh) == 3 and all(c < m for c, m in zip(coords, mesh)), (coords, mesh)
          import jax
          jax.config.update("jax_platforms", "cpu")
          from tpudra.workload.envspec import ClaimEnv
          env = ClaimEnv.from_environ()
          assert env.num_hosts == 2, env.num_hosts
          assert env.coordinator, "grant injected no coordinator"
          assert env.apply_libtpu_env()["TPU_WORKER_ID"] == str(env.worker_id)
          env.coordinator = os.environ.get("TPUDRA_SIM_COORDINATOR") or env.coordinator
          env.initialize_distributed()
          assert jax.process_count() == 2
          import numpy as np
          import jax.numpy as jnp
          from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
          from jax.experimental import multihost_utils
          mesh = Mesh(np.asarray(jax.devices()), ("dp",))
          local = jnp.ones((1, 4), jnp.float32) * (env.host_index + 1)
          garr = multihost_utils.host_local_array_to_global_array(local, mesh, P("dp", None))
          total = jax.jit(lambda a: a.sum(), out_shardings=NamedSharding(mesh, P()))(garr)
          val = float(total.addressable_data(0))
          assert val == 12.0, val  # (1 + 2) * 4 across both hosts
          print("RESULT psum:", val, "host", env.host_index)
      resources:
        claims:
          - name: channel
  resourceClaims:
    - name: channel
      resourceClaimTemplateName: coll-rct
EOF
  done
  kubectl apply -f "$TPUDRA_STATE/coll.yaml"
  wait_until 240 pod_succeeded worker-0 coll
  wait_until 240 pod_succeeded worker-1 coll
  run kubectl logs worker-0 -n coll
  [[ "$output" == *"RESULT psum: 12.0 host 0"* ]]
  run kubectl logs worker-1 -n coll
  [[ "$output" == *"RESULT psum: 12.0 host 1"* ]]
  # The relay was real: node-0's daemon served its coordinator proxy on
  # the port worker 1 dialed, and host 0 registered its live endpoint in
  # the shared domain dir.
  daemon0=$(kubectl get pods -n "$TPUDRA_NAMESPACE" -o name | grep -- computedomain-daemon | grep -- -node-0 | head -1)
  run kubectl logs "${daemon0#pods/}" -n "$TPUDRA_NAMESPACE"
  [[ "$output" == *"coordinator proxy on :$TPUDRA_COORD_PROXY_PORT"* ]]
  ls "$TPUDRA_STATE"/node-0/cdplugin/domains/*/coordinator
}

@test "rendezvous survives a daemon restart" {
  # Kill the node-0 daemon pod (the one serving the coordinator proxy).
  # The DaemonSet recreates it; the replacement rejoins the clique,
  # rebinds the proxy, and a FRESH worker pair must still rendezvous
  # through it — the elastic-recovery path for the relay (the analog of
  # the reference's daemon-failover assertions, test_cd_failover.bats).
  old0=$(kubectl get pods -n "$TPUDRA_NAMESPACE" -o name | grep -- computedomain-daemon | grep -- -node-0 | head -1)
  old0="${old0#pods/}"
  old_uid=$(kubectl get pod "$old0" -n "$TPUDRA_NAMESPACE" -o jsonpath='{.metadata.uid}')
  kubectl delete pod "$old0" -n "$TPUDRA_NAMESPACE"
  # The replacement reuses the deterministic pod name — key on the UID.
  daemon_replaced() {
    local uid
    uid=$(kubectl get pod "$old0" -n "$TPUDRA_NAMESPACE" -o jsonpath='{.metadata.uid}' 2>/dev/null)
    [ -n "$uid" ] && [ "$uid" != "$old_uid" ]
  }
  wait_until 120 daemon_replaced
  cd_ready() {
    kubectl get computedomain coll -n coll -o jsonpath='{.status.status}' | grep -q Ready
  }
  wait_until 180 cd_ready

  # Second worker pair, same ports: the old worker-0 is dead so its bind
  # port is free, and its stale registration is overwritten on start.
  # Only the POD docs are re-applied — re-PUTting the ComputeDomain doc
  # would transiently strip the controller's finalizer (full-object
  # update semantics) and race the teardown choreography.
  python3 - "$TPUDRA_STATE/coll.yaml" > "$TPUDRA_STATE/coll2.yaml" <<'PYEOF'
import sys, yaml
docs = [d for d in yaml.safe_load_all(open(sys.argv[1])) if d and d["kind"] == "Pod"]
for d in docs:
    d["metadata"]["name"] = d["metadata"]["name"].replace("worker-", "worker2-")
print(yaml.safe_dump_all(docs))
PYEOF
  kubectl apply -f "$TPUDRA_STATE/coll2.yaml"
  wait_until 240 pod_succeeded worker2-0 coll
  wait_until 240 pod_succeeded worker2-1 coll
  run kubectl logs worker2-1 -n coll
  [[ "$output" == *"RESULT psum: 12.0 host 1"* ]]
  # The replacement daemon served the proxy: same deterministic pod name,
  # but logs are per pod INSTANCE, so this reads the new pod's log only.
  run kubectl logs "$old0" -n "$TPUDRA_NAMESPACE"
  [[ "$output" == *"coordinator proxy on :$TPUDRA_COORD_PROXY_PORT"* ]]
}

@test "stale host-0 registration is probed, dropped, and recovered from" {
  # The worst staleness case: the host-0 WORKLOAD (not the daemon) died
  # after registering — the registration points at a dead address and
  # nothing will ever overwrite it if the replacement runs under another
  # uid (the domain dir is sticky-bit shared).  The daemon's coordinator
  # proxy must probe-and-drop it (coordproxy.py drop_after), turning the
  # peer's connect timeouts into fast retries, then relay the replacement
  # pair's rendezvous — all in well under jax's 300 s timeout.
  reg=$(ls "$TPUDRA_STATE"/node-0/cdplugin/domains/*/coordinator)
  echo "127.0.0.1:1" > "$reg"   # dead endpoint: connect refused instantly

  # Peer first: its jax client dials the proxy, which burns 3 failed
  # forwards to the dead endpoint and drops the registration.
  python3 - "$TPUDRA_STATE/coll.yaml" worker3-1 > "$TPUDRA_STATE/coll3-peer.yaml" <<'PYEOF'
import sys, yaml
docs = [d for d in yaml.safe_load_all(open(sys.argv[1])) if d and d["kind"] == "Pod"]
docs = [d for d in docs if d["metadata"]["name"] == sys.argv[2].replace("worker3-", "worker-")]
for d in docs:
    d["metadata"]["name"] = sys.argv[2]
print(yaml.safe_dump_all(docs))
PYEOF
  kubectl apply -f "$TPUDRA_STATE/coll3-peer.yaml"
  daemon_dropped_stale() {
    local d
    d=$(kubectl get pods -n "$TPUDRA_NAMESPACE" -o name | grep -- computedomain-daemon | grep -- -node-0 | head -1)
    kubectl logs "${d#pods/}" -n "$TPUDRA_NAMESPACE" | grep -q "dropped stale coordinator registration"
  }
  wait_until 120 daemon_dropped_stale
  [ ! -e "$reg" ]

  # Replacement host 0: registers its live endpoint; the already-running
  # peer's next retry is spliced through and both finish the psum.
  python3 - "$TPUDRA_STATE/coll.yaml" worker3-0 > "$TPUDRA_STATE/coll3-h0.yaml" <<'PYEOF'
import sys, yaml
docs = [d for d in yaml.safe_load_all(open(sys.argv[1])) if d and d["kind"] == "Pod"]
docs = [d for d in docs if d["metadata"]["name"] == sys.argv[2].replace("worker3-", "worker-")]
for d in docs:
    d["metadata"]["name"] = sys.argv[2]
print(yaml.safe_dump_all(docs))
PYEOF
  kubectl apply -f "$TPUDRA_STATE/coll3-h0.yaml"
  wait_until 240 pod_succeeded worker3-0 coll
  wait_until 240 pod_succeeded worker3-1 coll
  run kubectl logs worker3-1 -n coll
  [[ "$output" == *"RESULT psum: 12.0 host 1"* ]]
}

@test "teardown" {
  # --ignore-not-found: a failure in the restart test before coll2.yaml
  # applies must not cascade into a second (misattributed) failure here.
  kubectl delete pod worker-0 worker-1 worker2-0 worker2-1 worker3-0 worker3-1 -n coll --ignore-not-found
  kubectl delete computedomains coll -n coll
  wait_until 120 sh -c "! kubectl get computedomains -n coll -o name | grep -q coll"
}

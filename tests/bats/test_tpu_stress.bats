#!/usr/bin/env bats
# Claim churn under parallelism (the reference's test_gpu_stress.bats
# analog): waves of pods racing for every chip on two nodes; everything
# binds, runs, and frees.

load helpers.sh

setup_file() {
  cluster_up --nodes 2 --chips-per-node 4
}

teardown_file() {
  cluster_down
}

make_wave() {
  local wave="$1" count="$2"
  : > "$TPUDRA_STATE/wave.yaml"
  for i in $(seq 1 "$count"); do
    cat >> "$TPUDRA_STATE/wave.yaml" <<EOF
apiVersion: resource.k8s.io/v1
kind: ResourceClaimTemplate
metadata:
  namespace: default
  name: stress-$wave-$i
spec:
  spec:
    devices:
      requests:
        - name: tpu
          exactly:
            deviceClassName: tpu.google.com
---
apiVersion: v1
kind: Pod
metadata:
  namespace: default
  name: stress-$wave-$i
spec:
  restartPolicy: Never
  containers:
    - name: ctr
      image: tpudra-workload:latest
      command: ["python", "-c", "import os; print('chip', os.environ['TPU_VISIBLE_DEVICES'])"]
      resources:
        claims: [{name: tpu}]
  resourceClaims:
    - name: tpu
      resourceClaimTemplateName: stress-$wave-$i
---
EOF
  done
}

@test "wave 1: 8 single-chip pods saturate both nodes and all succeed" {
  make_wave 1 8
  kubectl apply -f "$TPUDRA_STATE/wave.yaml"
  for i in $(seq 1 8); do
    wait_until 120 pod_succeeded "stress-1-$i" default
  done
  # Every chip was used exactly once: 8 distinct (node, chip) grants.
  grants=$(for i in $(seq 1 8); do
    node=$(kubectl get pod "stress-1-$i" -o 'jsonpath={.spec.nodeName}')
    chip=$(kubectl logs "stress-1-$i" | grep '^chip ')
    echo "$node/$chip"
  done | sort -u | wc -l)
  [ "$grants" -eq 8 ]
}

@test "a 9th pod stays pending until the wave is deleted" {
  make_wave 2 1
  kubectl apply -f "$TPUDRA_STATE/wave.yaml"
  sleep 2
  [ "$(pod_phase stress-2-1 default)" != "Succeeded" ]
  for i in $(seq 1 8); do kubectl delete pod "stress-1-$i" >/dev/null; done
  wait_until 120 pod_succeeded stress-2-1 default
}

@test "wave 3 reuses every freed chip" {
  kubectl delete pod stress-2-1 >/dev/null
  make_wave 3 8
  kubectl apply -f "$TPUDRA_STATE/wave.yaml"
  for i in $(seq 1 8); do
    wait_until 120 pod_succeeded "stress-3-$i" default
  done
  for i in $(seq 1 8); do kubectl delete pod "stress-3-$i" >/dev/null; done
}

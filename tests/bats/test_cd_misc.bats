#!/usr/bin/env bats
# ComputeDomain odds and ends (the reference's test_cd_misc.bats analog):
# allocationMode All injects the full 2048-channel set, and a node's
# fabric resources are reusable by a successor domain after teardown.

load helpers.sh

setup_file() {
  cluster_up --nodes 1 --cd
}

teardown_file() {
  cluster_down
}

@test "allocationMode All injects all 2048 channels" {
  apply_spec domain/channel-injection-all.yaml
  wait_until 240 pod_succeeded chan-all-pod tpu-domain-demo
  run kubectl logs chan-all-pod -n tpu-domain-demo
  [[ "$output" == *"2048 channels"* ]]
}

@test "teardown of the first domain completes" {
  kubectl delete pod chan-all-pod -n tpu-domain-demo
  kubectl delete computedomains chan-all -n tpu-domain-demo
  wait_until 120 sh -c "! kubectl get computedomains -n tpu-domain-demo -o name | grep -q chan-all"
  wait_until 120 sh -c "! kubectl get daemonsets -n $TPUDRA_NAMESPACE -o name | grep -q computedomain-daemon"
}

@test "a successor domain forms on the same node" {
  apply_spec domain/channel-injection.yaml
  wait_until 240 pod_succeeded chan-single-pod tpu-domain-demo
  run kubectl logs chan-single-pod -n tpu-domain-demo
  [[ "$output" == *"channels ['0']"* ]]
  kubectl delete pod chan-single-pod -n tpu-domain-demo
  kubectl delete computedomains chan-single -n tpu-domain-demo
  wait_until 120 sh -c "! kubectl get computedomains -n tpu-domain-demo -o name | grep -q chan-single"
}

@test "no cliques or claims leak after both domains are gone" {
  wait_until 60 sh -c "! kubectl get computedomaincliques -n $TPUDRA_NAMESPACE -o name | grep -q ."
  run kubectl get resourceclaims -n tpu-domain-demo -o name
  [ -z "$output" ]
}

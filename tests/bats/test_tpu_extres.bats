#!/usr/bin/env bats
# extendedResourceName path (the reference's test_gpu_extres.bats analog):
# a pod requests plain `resources.limits: {tpu.google.com/chip: N}` with no
# resourceClaims stanza; the DRA-aware scheduler authors the claim.

load helpers.sh

setup_file() {
  cluster_up --nodes 1 --chips-per-node 4
}

teardown_file() {
  cluster_down
}

@test "pod with extended-resource limits gets chips via an authored claim" {
  cat > "$TPUDRA_STATE/extres.yaml" <<'EOF'
apiVersion: v1
kind: Pod
metadata:
  namespace: default
  name: extres-pod
spec:
  restartPolicy: Never
  containers:
    - name: ctr
      image: tpudra-workload:latest
      command: ["python", "-c"]
      args:
        - |
          import os
          vis = os.environ["TPU_VISIBLE_DEVICES"].split(",")
          assert len(vis) == 2, vis
          print("extres got", len(vis))
      resources:
        limits:
          tpu.google.com/chip: 2
EOF
  kubectl apply -f "$TPUDRA_STATE/extres.yaml"
  wait_until 60 pod_succeeded extres-pod default
  run kubectl logs extres-pod
  [[ "$output" == *"extres got 2"* ]]
}

@test "the scheduler-authored claim exists and is owned by the pod" {
  run kubectl get resourceclaims extres-pod-extended-resources -o json
  [ "$status" -eq 0 ]
  [[ "$output" == *'"kind": "Pod"'* ]]
}

@test "deleting the pod garbage-collects the authored claim" {
  kubectl delete pod extres-pod
  wait_until 30 sh -c "! kubectl get resourceclaims extres-pod-extended-resources -o name 2>/dev/null | grep -q extres"
}

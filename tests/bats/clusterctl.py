#!/usr/bin/env python3
"""clusterctl — bring the hermetic cluster up/down for the bats e2e suite.

The analog of the reference's ``tests/bats/helpers.sh`` install step (helm
install into a kubectl-pointed cluster, helpers.sh:42-60), except nothing
external is needed: `up` starts

- the fake apiserver over HTTP (tpudra/kube/httpserver.py),
- per-node TPU kubelet plugins (and, with --cd, ComputeDomain kubelet
  plugins, the controller, and per-node fabric identity),
- the scheduler/kubelet simulator (tpu-cluster-sim),

registers Node objects, applies the chart's DeviceClasses (the "helm
install" of the hermetic world), waits for ResourceSlice publication, and
writes ``env.sh`` with the environment the bats files source.  `down`
SIGTERMs everything it started, newest first.

State-dir layout (keep the dir SHORT — AF_UNIX socket paths live in it):

    <state>/apiserver.url      <state>/pids
    <state>/env.sh             <state>/sim.json
    <state>/<node>/{plugin,cdplugin,registry,cdi,cdwork,hosts,logs}
"""

from __future__ import annotations

import argparse
import json
import os
import re
import signal
import socket
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(os.path.dirname(HERE))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))

CHART = os.path.join(REPO, "deployments", "helm", "tpu-dra-driver")
NATIVE_BUILD = os.path.join(REPO, "native", "build")
NAMESPACE = "tpudra-system"


def free_ports(n: int) -> list[int]:
    socks = [socket.socket() for _ in range(n)]
    try:
        for sk in socks:
            sk.bind(("127.0.0.1", 0))
        return [sk.getsockname()[1] for sk in socks]
    finally:
        for sk in socks:
            sk.close()


def wait_for(fn, timeout: float, msg: str):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            v = fn()
        except Exception:  # noqa: BLE001 — the cluster is still booting
            v = None
        if v:
            return v
        time.sleep(0.1)
    raise SystemExit(f"clusterctl: timed out waiting for {msg}")


def record_pid(state: str, pid: int, what: str) -> None:
    with open(os.path.join(state, "pids"), "a") as f:
        f.write(f"{pid}\t{what}\n")


def spawn(state: str, what: str, argv: list[str], env: dict) -> subprocess.Popen:
    log_dir = os.path.join(state, "logs")
    os.makedirs(log_dir, exist_ok=True)
    log = open(os.path.join(log_dir, f"{what}.log"), "a")
    proc = subprocess.Popen(
        argv, env=env, stdout=log, stderr=subprocess.STDOUT, start_new_session=True
    )
    log.close()
    record_pid(state, proc.pid, what)
    # Record how to respawn, for `clusterctl restart` (failover tests).
    procs_path = os.path.join(state, "procs.json")
    try:
        procs = json.load(open(procs_path))
    except FileNotFoundError:
        procs = {}
    procs[what] = {"argv": argv, "env": env, "pid": proc.pid}
    with open(procs_path + ".tmp", "w") as f:
        json.dump(procs, f)
    os.replace(procs_path + ".tmp", procs_path)
    return proc


def wait_for_exit(pid: int, timeout: float, what: str = "") -> None:
    """Wait for a process to die; escalate to SIGKILL past the deadline."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            return
        time.sleep(0.1)
    if what:
        print(f"clusterctl: {what} ({pid}) did not exit; SIGKILL", file=sys.stderr)
    try:
        os.killpg(pid, signal.SIGKILL)
    except (OSError, ProcessLookupError):
        pass


def base_env(server_url: str) -> dict:
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["KUBE_API_SERVER"] = server_url
    env["PYTHONUNBUFFERED"] = "1"
    env.pop("KUBECONFIG", None)
    return env


# ----------------------------------------------------------------- serve


def _install_admission(fake, webhook_url: str) -> None:
    """Route ResourceClaim(Template) writes through the validating webhook
    (what the real apiserver's ValidatingWebhookConfiguration does): a
    denial rejects the write.  failurePolicy=Ignore while the webhook is
    still booting."""
    import json as _json
    import urllib.error
    import urllib.request

    from tpudra.kube import errors, gvr

    def admission_reactor(verb, g, obj):
        if obj is None:
            return
        review = {
            "apiVersion": "admission.k8s.io/v1",
            "kind": "AdmissionReview",
            "request": {
                "uid": "sim-admission",
                "object": obj,
            },
        }
        req = urllib.request.Request(
            webhook_url,
            data=_json.dumps(review).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            resp = _json.loads(urllib.request.urlopen(req, timeout=5).read())
        except (OSError, ValueError):
            return  # failurePolicy: Ignore
        response = resp.get("response", {})
        if not response.get("allowed", True):
            msg = response.get("status", {}).get("message", "denied")
            raise errors.BadRequest(f"admission webhook denied the request: {msg}")

    # Create only: a claim's spec is immutable after creation, and FakeKube
    # routes every status/patch write through the "update" verb — reacting
    # there would hold the apiserver's global lock for a webhook round-trip
    # on each of the driver's frequent status writes.
    for g in (gvr.RESOURCE_CLAIMS, gvr.RESOURCE_CLAIM_TEMPLATES):
        fake.react("create", g, admission_reactor)


def cmd_serve(args) -> int:
    from tpudra.kube.httpserver import FakeKubeServer

    server = FakeKubeServer()
    if args.webhook_url:
        _install_admission(server.fake, args.webhook_url)
    server.start()
    with open(args.url_file + ".tmp", "w") as f:
        f.write(server.url)
    os.replace(args.url_file + ".tmp", args.url_file)
    stop = []
    signal.signal(signal.SIGTERM, lambda *_: stop.append(1))
    signal.signal(signal.SIGINT, lambda *_: stop.append(1))
    while not stop:
        time.sleep(0.2)
    server.stop()
    return 0


def _mk_fake_sysfs(node_dir: str, topo: dict) -> str:
    """Fake sysfs for the node's mock chips (shared layout:
    devicelib.mock.fake_sysfs_tree)."""
    from tpudra.devicelib import MockTopologyConfig
    from tpudra.devicelib.mock import MockDeviceLib, fake_sysfs_tree

    lib = MockDeviceLib(config=MockTopologyConfig.from_json(json.dumps(topo)))
    return fake_sysfs_tree(node_dir, lib.enumerate_chips())


# -------------------------------------------------------------------- up


_ORPHAN_MARKERS = ("tpudra", "clusterctl", "tpu-slicewatchd", "tpu-mp-control")


def reap_stale_orphans() -> int:
    """Kill processes left over from SIGKILLed/aborted cluster runs.

    A hermetic cluster's processes are recorded in <state>/procs.json and
    torn down by ``down`` — but a runner killed with SIGKILL (CI timeout,
    Ctrl-Z'd shell, aborted soak) never runs teardown, and the survivors
    keep polling a dead apiserver forever (observed: 100+ daemons from one
    round of aborted runs, distorting every co-resident benchmark).  The
    heuristic is strict on purpose: only processes that (a) look like ours
    (cmdline mentions tpudra/clusterctl/tpu-slicewatchd/tpu-mp-control) and
    (b) reference a ``/tmp/tpubats-*`` state dir — in cmdline or environ —
    that NO LONGER EXISTS are reaped — and only when the executable is
    one of ours (python/our native binaries): an operator's pager or grep
    holding a path like .../tpubats-gone/clusterctl.log must never be
    collateral.  Never self or ancestors."""
    state_dir_re = re.compile(rb"(/tmp/tpubats-[A-Za-z0-9_]{4,16})")
    me = os.getpid()
    ancestors = set()
    pid = me
    while pid > 1:
        try:
            with open(f"/proc/{pid}/stat") as f:
                pid = int(f.read().split(")")[-1].split()[1])
            ancestors.add(pid)
        except OSError:
            break
    reaped = 0
    for pid_s in os.listdir("/proc"):
        if not pid_s.isdigit():
            continue
        pid = int(pid_s)
        if pid == me or pid in ancestors:
            continue
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                cmdline = f.read()
            argv0 = os.path.basename(cmdline.split(b"\0", 1)[0]).decode(
                errors="replace"
            )
            if not (argv0.startswith("python") or argv0.startswith("tpu-")):
                continue
            if not any(m.encode() in cmdline for m in _ORPHAN_MARKERS):
                continue
            with open(f"/proc/{pid}/environ", "rb") as f:
                blob = cmdline + f.read()
        except OSError:
            continue  # raced away or not ours to inspect
        dirs = set(state_dir_re.findall(blob))
        if not dirs or any(os.path.isdir(d.decode()) for d in dirs):
            continue  # no state-dir tie, or its cluster is still live
        try:
            os.kill(pid, signal.SIGKILL)
            reaped += 1
        except OSError:
            pass
    if reaped:
        print(f"reaped {reaped} stale process(es) from dead state dirs",
              file=sys.stderr)
    return reaped


def cmd_up(args) -> int:
    from tpudra.kube import gvr
    from tpudra.kube.client import KubeClient
    from helmlite import Chart

    # Self-healing: every cluster boot clears the debris of previously
    # aborted runs before adding its own processes.
    reap_stale_orphans()

    state = args.state
    os.makedirs(state, exist_ok=True)
    open(os.path.join(state, "pids"), "w").close()

    url_file = os.path.join(state, "apiserver.url")
    serve_argv = [sys.executable, HERE + "/clusterctl.py", "serve",
                  "--url-file", url_file]
    webhook_port = 0
    if args.webhook:
        webhook_port = free_ports(1)[0]
        serve_argv += ["--webhook-url",
                       f"http://127.0.0.1:{webhook_port}"
                       "/validate-resource-claim-parameters"]
    serve_env = dict(os.environ)
    serve_env["PYTHONPATH"] = REPO + os.pathsep + serve_env.get("PYTHONPATH", "")
    spawn(state, "apiserver", serve_argv, serve_env)
    wait_for(lambda: os.path.exists(url_file), 30, "apiserver URL")
    server_url = open(url_file).read().strip()
    kube = KubeClient(server_url)
    wait_for(lambda: kube.list(gvr.PODS) is not None, 30, "apiserver answering")
    env = base_env(server_url)

    nodes = [f"node-{i}" for i in range(args.nodes)]
    for n in nodes:
        kube.create(gvr.NODES, {
            "apiVersion": "v1", "kind": "Node",
            "metadata": {"name": n, "labels": {"kubernetes.io/hostname": n}},
        })

    # "helm install": the chart's DeviceClasses are the scheduler-facing
    # surface; the driver binaries below are the chart's DaemonSet payload.
    rendered = Chart(CHART).render(namespace=NAMESPACE)
    for docs in rendered.values():
        for doc in docs:
            if doc and doc.get("kind") == "DeviceClass":
                kube.create(gvr.DEVICE_CLASSES, doc)

    # Fabric identity for --cd: one slice spanning all nodes.  ONE batch:
    # free_ports holds every socket until all are read, so ports within a
    # batch cannot collide — separate batches could hand out duplicates.
    batch = free_ports(args.nodes * 3 + 2)
    peer_ports = batch[: args.nodes]
    status_ports = batch[args.nodes : args.nodes * 2]
    health_ports = batch[args.nodes * 2 : args.nodes * 3]
    port_map = ",".join(f"{i}={p}" for i, p in enumerate(peer_ports))
    # Coordinator proxy: all "hosts" share this machine, so only node 0's
    # daemon binds a proxy port (the others would EADDRINUSE each other);
    # exported as TPUDRA_COORD_PROXY_PORT for tests that dial it, plus a
    # scratch port from the same batch for tests that need a second
    # guaranteed-distinct endpoint (the collective test's host-0 bind).
    coord_proxy_port, scratch_port = batch[args.nodes * 3 :]

    sim_nodes = []
    for i, n in enumerate(nodes):
        nd = os.path.join(state, n)
        for sub in ("plugin", "cdplugin", "registry", "cdi", "cdwork"):
            os.makedirs(os.path.join(nd, sub), exist_ok=True)
        hosts = os.path.join(nd, "hosts")
        open(hosts, "a").close()
        topo = {
            "generation": args.generation,
            "num_chips": args.chips_per_node,
            "slice_uuid": "bats-slice",
            "host_index": i,
            "num_hosts": args.nodes,
        }
        if args.static_partitions:
            topo["static_partitions"] = [
                [int(c), prof, int(cs), int(hs)]
                for c, prof, cs, hs in (
                    p.split(":") for p in args.static_partitions.split(",")
                )
            ]
        plug_env = dict(
            env,
            NODE_NAME=n,
            TPUDRA_MOCK_TOPOLOGY=json.dumps(topo),
        )
        if args.feature_gates:
            plug_env["FEATURE_GATES"] = args.feature_gates
        plugin_extra_argv = []
        if args.vfio:
            plugin_extra_argv += [
                "--sysfs-root", _mk_fake_sysfs(nd, topo),
            ]
        backend = "mock"
        if args.native_backend:
            # The real C++ enumeration library in config-file mode, with the
            # file-driven health event channel (TPUINFO_HEALTH_EVENTS).
            backend = "native"
            cfg_path = os.path.join(nd, "tpuinfo.cfg")
            with open(cfg_path, "w") as f:
                for k, v in {
                    # Same per-node topology as the mock path (one source
                    # of truth); static_partitions has no tpuinfo.cfg key.
                    **{k: v for k, v in topo.items() if k != "static_partitions"},
                    "partition_id": "0",
                    "state_file": os.path.join(nd, "tpuinfo-state"),
                }.items():
                    f.write(f"{k}={v}\n")
            health_events = os.path.join(nd, "health-events")
            open(health_events, "a").close()
            plug_env["TPUINFO_LIBRARY_PATH"] = os.path.join(
                NATIVE_BUILD, "libtpuinfo.so"
            )
            plug_env["TPUINFO_HEALTH_EVENTS"] = health_events
            plugin_extra_argv += ["--tpuinfo-config", cfg_path]
        spawn(state, f"plugin-{n}", [
            sys.executable, "-m", "tpudra.plugin.main",
            "--node-name", n,
            "--plugin-dir", os.path.join(nd, "plugin"),
            "--registry-dir", os.path.join(nd, "registry"),
            "--cdi-root", os.path.join(nd, "cdi"),
            "--device-backend", backend,
            "--healthcheck-port", str(health_ports[i]),
            *plugin_extra_argv,
        ], plug_env)
        drivers = {"tpu.google.com": os.path.join(nd, "plugin", "dra.sock")}
        if args.cd:
            spawn(state, f"cdplugin-{n}", [
                sys.executable, "-m", "tpudra.cdplugin.main",
                "--node-name", n,
                "--plugin-dir", os.path.join(nd, "cdplugin"),
                "--registry-dir", os.path.join(nd, "registry"),
                "--cdi-root", os.path.join(nd, "cdi"),
                "--device-backend", "mock",
            ], plug_env)
            drivers["compute-domain.tpu.google.com"] = os.path.join(
                nd, "cdplugin", "dra.sock"
            )
        sim_nodes.append({
            "name": n,
            "drivers": drivers,
            "cdi_roots": [os.path.join(nd, "cdi")],
            "env": {
                "PATH": NATIVE_BUILD + os.pathsep + os.environ.get("PATH", ""),
                "TPUDRA_SIM_JAX_CPU": "1",
                "STATUS_PORT": str(status_ports[i]),
                "TPUDRA_PEER_PORT_MAP": port_map,
                "HOSTS_PATH": hosts,
                "WORK_DIR": os.path.join(nd, "cdwork"),
                "COORDINATOR_PORT": str(coord_proxy_port if i == 0 else 0),
            },
        })

    if args.cd:
        spawn(state, "controller", [
            sys.executable, "-m", "tpudra.controller.main",
            "--namespace", NAMESPACE,
        ], env)

    if args.webhook:
        webhook_env = dict(env)
        if args.feature_gates:
            webhook_env["FEATURE_GATES"] = args.feature_gates
        spawn(state, "webhook", [
            sys.executable, "-m", "tpudra.webhook.main",
            "--port", str(webhook_port),
        ], webhook_env)

        def webhook_answering():
            import json as _json
            import urllib.request

            review = {"apiVersion": "admission.k8s.io/v1",
                      "kind": "AdmissionReview",
                      "request": {"uid": "probe", "object": {}}}
            req = urllib.request.Request(
                f"http://127.0.0.1:{webhook_port}"
                "/validate-resource-claim-parameters",
                data=_json.dumps(review).encode(),
                headers={"Content-Type": "application/json"},
            )
            return _json.loads(urllib.request.urlopen(req, timeout=2).read())

        wait_for(webhook_answering, 30, "webhook answering")

    sim_cfg = {
        "server": server_url,
        "nodes": sim_nodes,
        "env": {
            "KUBE_API_SERVER": server_url,
            "PYTHONPATH": env["PYTHONPATH"],
        },
    }
    sim_path = os.path.join(state, "sim.json")
    with open(sim_path, "w") as f:
        json.dump(sim_cfg, f, indent=2)
    spawn(state, "cluster-sim", [
        sys.executable, "-m", "tpudra.sim.main", "--config", sim_path,
    ], env)

    # Readiness: every node's TPU pool published; with --cd, every node's
    # channel pool too (2048 channels + daemon arrive chunked).
    def slices_ready():
        items = kube.list(gvr.RESOURCE_SLICES).get("items", [])
        tpu_nodes = {s["spec"].get("nodeName") for s in items
                     if s["spec"]["driver"] == "tpu.google.com"}
        if set(nodes) - tpu_nodes:
            return False
        if args.cd:
            cd_nodes = {s["spec"].get("nodeName") for s in items
                        if s["spec"]["driver"] == "compute-domain.tpu.google.com"}
            if set(nodes) - cd_nodes:
                return False
        return True

    wait_for(slices_ready, 90, "ResourceSlice publication")

    with open(os.path.join(state, "env.sh"), "w") as f:
        f.write(
            f'export KUBE_API_SERVER="{server_url}"\n'
            f'export TPUDRA_STATE="{state}"\n'
            f'export TPUDRA_NAMESPACE="{NAMESPACE}"\n'
            f'export TPUDRA_NODES="{" ".join(nodes)}"\n'
            f'export TPUDRA_COORD_PROXY_PORT="{coord_proxy_port}"\n'
            f'export TPUDRA_SCRATCH_PORT="{scratch_port}"\n'
            f'export TPUDRA_HEALTH_PORTS="'
            f'{" ".join(f"{n}={p}" for n, p in zip(nodes, health_ports))}"\n'
            f'export PYTHONPATH="{env["PYTHONPATH"]}"\n'
            f'export PATH="{os.path.join(REPO, "tests", "bats", "bin")}:'
            f'{os.environ.get("PATH", "")}"\n'
        )
    print(state)
    return 0


# ------------------------------------------------------------------ down


def cmd_kill(args) -> int:
    """SIGKILL one recorded process (failover tests kill daemons mid-run,
    the reference's test_cd_failover.bats / lib/test_cd_nvb_failover.sh)."""
    procs = json.load(open(os.path.join(args.state, "procs.json")))
    pid = procs[args.what]["pid"]
    try:
        os.killpg(pid, signal.SIGKILL)
    except (OSError, ProcessLookupError):
        try:
            os.kill(pid, signal.SIGKILL)
        except (OSError, ProcessLookupError):
            return 1
    return 0


def cmd_restart(args) -> int:
    """Respawn a recorded process by name (driver restart tests)."""
    procs = json.load(open(os.path.join(args.state, "procs.json")))
    entry = procs[args.what]
    try:
        os.killpg(entry["pid"], signal.SIGTERM)
    except (OSError, ProcessLookupError):
        pass
    wait_for_exit(entry["pid"], 10, args.what)
    spawn(args.state, args.what, entry["argv"], entry["env"])
    return 0


def cmd_down(args) -> int:
    pids_file = os.path.join(args.state, "pids")
    try:
        entries = [line.split("\t") for line in open(pids_file).read().splitlines()]
    except FileNotFoundError:
        return 0
    for pid_s, _what in reversed(entries):
        try:
            os.killpg(int(pid_s), signal.SIGTERM)
        except (OSError, ProcessLookupError):
            try:
                os.kill(int(pid_s), signal.SIGTERM)
            except (OSError, ProcessLookupError):
                pass
    deadline = time.monotonic() + 15
    for pid_s, what in reversed(entries):
        wait_for_exit(int(pid_s), max(0.0, deadline - time.monotonic()), what)
    os.unlink(pids_file)
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="clusterctl", description=__doc__)
    sub = p.add_subparsers(dest="verb", required=True)

    sp = sub.add_parser("serve")
    sp.add_argument("--url-file", required=True)
    sp.add_argument("--webhook-url", default="")
    sp.set_defaults(fn=cmd_serve)

    up = sub.add_parser("up")
    up.add_argument("--state", required=True)
    up.add_argument("--nodes", type=int, default=1)
    up.add_argument("--cd", action="store_true",
                    help="also start CD plugins + controller + fabric identity")
    up.add_argument("--webhook", action="store_true",
                    help="start the admission webhook and route claim writes "
                    "through it")
    up.add_argument("--generation", default="v5p")
    up.add_argument("--chips-per-node", type=int, default=4)
    up.add_argument("--feature-gates", default="",
                    help="FEATURE_GATES for the driver binaries")
    up.add_argument("--static-partitions", default="",
                    help="chip:profile:core_start:hbm_start[,...] per node")
    up.add_argument("--vfio", action="store_true",
                    help="fabricate a per-node sysfs tree and point the "
                    "plugin's vfio rebind path at it")
    up.add_argument("--native-backend", action="store_true",
                    help="TPU plugins use the C++ libtpuinfo backend in "
                    "config-file mode (health events via file)")
    up.set_defaults(fn=cmd_up)

    dn = sub.add_parser("down")
    dn.add_argument("--state", required=True)
    dn.set_defaults(fn=cmd_down)

    kp = sub.add_parser("kill")
    kp.add_argument("--state", required=True)
    kp.add_argument("--what", required=True)
    kp.set_defaults(fn=cmd_kill)

    rp = sub.add_parser("restart")
    rp.add_argument("--state", required=True)
    rp.add_argument("--what", required=True)
    rp.set_defaults(fn=cmd_restart)

    args = p.parse_args(argv)
    if getattr(args, "native_backend", False) and getattr(
        args, "static_partitions", ""
    ):
        p.error("--static-partitions is mock-only; the native config file "
                "has no static-partitions key")
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())

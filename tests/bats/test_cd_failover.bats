#!/usr/bin/env bats
# ComputeDomain failover (the reference's test_cd_failover.bats analog):
# kill daemons mid-run; the DaemonSet re-stamps the pod, the daemon rejoins
# the clique reusing its index, and the domain returns to Ready while the
# workload keeps running.

load helpers.sh

setup_file() {
  cluster_up --nodes 2 --cd
}

teardown_file() {
  cluster_down
}

@test "form a 2-node domain with long-running workers" {
  cat > "$TPUDRA_STATE/cdf.yaml" <<'EOF'
apiVersion: resource.tpu.google.com/v1beta1
kind: ComputeDomain
metadata:
  namespace: cdf
  name: failover
spec:
  numNodes: 2
  channel:
    resourceClaimTemplate:
      name: failover-rct
    allocationMode: Single
EOF
  for n in 0 1; do
    cat >> "$TPUDRA_STATE/cdf.yaml" <<EOF
---
apiVersion: v1
kind: Pod
metadata:
  namespace: cdf
  name: worker-$n
spec:
  restartPolicy: Never
  hostNetwork: true  # multi-host channel contract (test_cd_hostnet.bats)
  nodeSelector:
    kubernetes.io/hostname: node-$n
  containers:
    - name: ctr
      image: tpudra-workload:latest
      command: ["python", "-c", "import time; time.sleep(600)"]
      resources:
        claims: [{name: channel}]
  resourceClaims:
    - name: channel
      resourceClaimTemplateName: failover-rct
EOF
  done
  kubectl apply -f "$TPUDRA_STATE/cdf.yaml"
  wait_until 240 sh -c "[ \"\$(kubectl get pods -n cdf -o 'jsonpath={.items[*].status.phase}')\" = 'Running Running' ]"
  wait_until 60 sh -c "kubectl get computedomains failover -n cdf -o 'jsonpath={.status.status}' | grep -q Ready"
}

@test "killing a daemon pod: DS re-stamps it and the domain recovers" {
  uid=$(kubectl get computedomains failover -n cdf -o 'jsonpath={.metadata.uid}')
  dspod="computedomain-daemon-$uid-node-1"
  kubectl get pod "$dspod" -n "$TPUDRA_NAMESPACE" -o name
  old_uid=$(kubectl get pod "$dspod" -n "$TPUDRA_NAMESPACE" -o 'jsonpath={.metadata.uid}')
  kubectl delete pod "$dspod" -n "$TPUDRA_NAMESPACE"
  # The DaemonSet controller stamps a fresh pod (new uid) on the node.
  wait_until 120 sh -c "new=\$(kubectl get pod '$dspod' -n '$TPUDRA_NAMESPACE' -o 'jsonpath={.metadata.uid}' 2>/dev/null); [ -n \"\$new\" ] && [ \"\$new\" != '$old_uid' ]"
  # The new daemon rejoins and the domain returns to (or stays) Ready.
  wait_until 180 sh -c "kubectl get computedomains failover -n cdf -o 'jsonpath={.status.status}' | grep -q Ready"
  # Workloads never died.
  run kubectl get pods -n cdf -o 'jsonpath={.items[*].status.phase}'
  [ "$output" = "Running Running" ]
}

@test "killing the native slicewatchd: the watchdog restarts it in place" {
  pkill -f "tpu-slicewatchd.*$TPUDRA_STATE/node-0" || skip "no slicewatchd match"
  sleep 3
  # Watchdog restart (process.py) brings the peer back; domain stays Ready.
  wait_until 120 sh -c "kubectl get computedomains failover -n cdf -o 'jsonpath={.status.status}' | grep -q Ready"
  run pgrep -f "tpu-slicewatchd.*$TPUDRA_STATE/node-0"
  [ "$status" -eq 0 ]
}

@test "teardown" {
  kubectl delete pod worker-0 worker-1 -n cdf
  kubectl delete computedomains failover -n cdf
  wait_until 90 sh -c "! kubectl get computedomains -n cdf -o name | grep -q failover"
}

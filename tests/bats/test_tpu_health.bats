#!/usr/bin/env bats
# Device health end to end on the NATIVE backend (reference
# device_health.go → driver.go:441-505): a fault event on the file-driven
# interrupt channel makes the plugin republish its ResourceSlices without
# the unhealthy chip, with no auto-reheal.

load helpers.sh

setup_file() {
  if [ ! -f "$REPO/native/build/libtpuinfo.so" ]; then
    echo "libtpuinfo.so not built (make -C native)" >&2
    return 1
  fi
  cluster_up --nodes 1 --chips-per-node 2 --native-backend \
    --feature-gates TPUDeviceHealthCheck=true,DRAResourceHealthService=true
}

teardown_file() {
  cluster_down
}

@test "the C++ backend enumerates and publishes both chips" {
  run kubectl get resourceslices -o json
  [[ "$output" == *'"tpu-0"'* ]]
  [[ "$output" == *'"tpu-1"'* ]]
}

@test "a fault event removes the chip from the published slices" {
  uuid=$(kubectl get resourceslices -o json | python3 -c '
import json, sys
for s in json.load(sys.stdin)["items"]:
    for d in s["spec"].get("devices", []):
        if d["name"] == "tpu-0":
            print(d["attributes"]["uuid"]["string"]); break
')
  [ -n "$uuid" ]
  echo "ChipLockup $uuid - bats-injected" >> "$TPUDRA_STATE/node-0/health-events"
  wait_until 60 sh -c "! kubectl get resourceslices -o json | grep -q '\"tpu-0\"'"
  run kubectl get resourceslices -o json
  [[ "$output" == *'"tpu-1"'* ]]
}

@test "kubelet-facing DRAResourceHealth stream reports the fault" {
  # The third service on the plugin socket (plugin/healthservice.py): act
  # as kubelet, open the v1alpha1 stream, and read a complete snapshot —
  # the faulted chip must be UNHEALTHY while its sibling stays HEALTHY,
  # telling the same story as the slice withdrawal above.
  run python3 -c "
import sys
from tpudra.plugin.healthservice import HealthWatchClient
c = HealthWatchClient('$TPUDRA_STATE/node-0/plugin/dra.sock')
snap = next(c.watch(timeout=20))
c.close()
print('HEALTH', ','.join(
    k + '=' + ('H' if v['healthy'] else 'U') for k, v in sorted(snap.items())))
"
  [ "$status" -eq 0 ]
  [[ "$output" == *"tpu-0=U"* ]]
  [[ "$output" == *"tpu-1=H"* ]]
}

@test "no auto-reheal: the chip stays withheld" {
  sleep 3
  run kubectl get resourceslices -o json
  ! echo "$output" | grep -q '"tpu-0"'
}

@test "new claims avoid the unhealthy chip" {
  cat > "$TPUDRA_STATE/healthy.yaml" <<'EOF'
apiVersion: resource.k8s.io/v1
kind: ResourceClaimTemplate
metadata:
  namespace: default
  name: healthy
spec:
  spec:
    devices:
      requests:
        - name: tpu
          exactly:
            deviceClassName: tpu.google.com
---
apiVersion: v1
kind: Pod
metadata:
  namespace: default
  name: healthy-pod
spec:
  restartPolicy: Never
  containers:
    - name: ctr
      image: tpudra-workload:latest
      command: ["python", "-c", "import os; print('got', os.environ['TPU_VISIBLE_DEVICES'])"]
      resources:
        claims: [{name: tpu}]
  resourceClaims:
    - name: tpu
      resourceClaimTemplateName: healthy
EOF
  kubectl apply -f "$TPUDRA_STATE/healthy.yaml"
  wait_until 60 pod_succeeded healthy-pod default
  run kubectl logs healthy-pod
  [[ "$output" == *"got 1"* ]]
  kubectl delete pod healthy-pod
}

@test "an ignored event kind does not withhold silicon" {
  uuid=$(kubectl get resourceslices -o json | python3 -c '
import json, sys
for s in json.load(sys.stdin)["items"]:
    for d in s["spec"].get("devices", []):
        if d["name"] == "tpu-1":
            print(d["attributes"]["uuid"]["string"]); break
')
  # IciLinkDown is on the default ignore list (XID-skip analog): a link
  # flap does not mean the chip itself is unusable.
  echo "IciLinkDown $uuid - flap" >> "$TPUDRA_STATE/node-0/health-events"
  sleep 3
  run kubectl get resourceslices -o json
  [[ "$output" == *'"tpu-1"'* ]]
}

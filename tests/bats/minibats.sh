#!/usr/bin/env bash
# minibats — a minimal bats-core-compatible runner (this environment ships
# no bats).  Supports the subset the suite uses: @test blocks, setup_file/
# teardown_file (run once, in the runner shell so exported variables
# persist), setup/teardown (per test, inside the test subshell), `run`
# (captures $status/$output/$lines), and `skip`.  Real bats-core runs these
# same files unmodified against a real cluster.
#
# Usage: minibats.sh FILE.bats [test-number ...]
set -u

FILE="${1:?usage: minibats.sh FILE.bats [n ...]}"
shift || true
ONLY=("$@")

TMP="$(mktemp -d /tmp/minibats-XXXXXX)"
trap 'rm -rf "$TMP"' EXIT

# Transform "@test \"name\" {" into numbered functions, collecting names.
awk -v namesfile="$TMP/names" '
  /^[ \t]*@test[ \t]/ {
    n++
    line=$0
    sub(/^[ \t]*@test[ \t]+"/, "", line)
    sub(/"[ \t]*\{[ \t]*$/, "", line)
    print n "\t" line >> namesfile
    print "__minibats_test_" n "() {"
    next
  }
  { print }
' "$FILE" > "$TMP/suite.sh"

COUNT=0
[ -f "$TMP/names" ] && COUNT=$(wc -l < "$TMP/names")

run() {
  local _rc=0
  set +e
  output="$("$@" 2>&1)"
  _rc=$?
  set -e
  status=$_rc
  # shellcheck disable=SC2034
  mapfile -t lines <<<"$output"
  return 0
}

skip() {
  echo "minibats-skip: ${1:-}" >&2
  exit 200
}

# bats' `load` builtin: source relative to the test file's directory.
BATS_TEST_DIRNAME="$(cd "$(dirname "$FILE")" && pwd)"
export BATS_TEST_DIRNAME
load() {
  local f="$1"
  [[ "$f" == /* ]] || f="$BATS_TEST_DIRNAME/$f"
  [ -f "$f" ] || f="$f.bash"
  # shellcheck disable=SC1090
  source "$f"
}

export MINIBATS=1
# shellcheck disable=SC1090
source "$TMP/suite.sh"

echo "1..$COUNT"
declare -F setup_file >/dev/null && { setup_file || { echo "not ok 0 setup_file"; exit 1; }; }

FAILED=0
while IFS=$'\t' read -r idx name; do
  if [ "${#ONLY[@]}" -gt 0 ]; then
    keep=""
    for o in "${ONLY[@]}"; do [ "$o" = "$idx" ] && keep=1; done
    [ -z "$keep" ] && continue
  fi
  out_file="$TMP/out-$idx"
  (
    # errexit must stay live inside the test body: never invoke the test
    # function from a condition/|| context (bash suppresses set -e there).
    set -eE
    trap 'declare -F teardown >/dev/null && teardown' EXIT
    if declare -F setup >/dev/null; then setup; fi
    "__minibats_test_$idx"
  ) >"$out_file" 2>&1
  rc=$?
  if [ "$rc" -eq 0 ]; then
    echo "ok $idx $name"
  elif [ "$rc" -eq 200 ]; then
    echo "ok $idx $name # SKIP"
  else
    echo "not ok $idx $name"
    sed 's/^/#   /' "$out_file"
    # Failure hook (the reference's dump discipline, test_gpu_basic.bats:18):
    # if the suite's helpers define dump_cluster_state, capture pods/claims/
    # slices + log tails as TAP comments, bounded.
    if declare -F dump_cluster_state >/dev/null; then
      dump_cluster_state 2>&1 | sed 's/^/#   dump: /' | head -80
    fi
    FAILED=$((FAILED + 1))
  fi
done < "$TMP/names"

declare -F teardown_file >/dev/null && { teardown_file || true; }

exit $((FAILED > 0))

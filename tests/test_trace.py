"""End-to-end claim tracing (tpudra/trace.py): span mechanics, the
disabled zero-allocation fast path, the flight recorder, and every
propagation edge the driver owns — gRPC metadata across the kubelet
boundary, the WAL traceparent across a crash, and the grant env across
the rank process boundary."""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import pytest

from tpudra import trace
from tpudra.kube import gvr
from tpudra.kube.fake import FakeKube

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def traced(tmp_path, monkeypatch):
    """Arm tracing into a per-test log; reset the module's sink/ring on
    both sides so tests never share a file or a flight recorder."""
    log = str(tmp_path / "trace.jsonl")
    monkeypatch.setenv(trace.ENV_TRACE, "1")
    monkeypatch.setenv(trace.ENV_TRACE_LOG, log)
    trace.reset_for_tests()
    yield log
    trace.reset_for_tests()


def read(log: str) -> list:
    trace.flush()
    return trace.read_log(log)


def by_name(spans: list, name: str) -> list:
    return [s for s in spans if s["name"] == name]


# ----------------------------------------------------------- span mechanics


class TestSpanMechanics:
    def test_nesting_parents_and_jsonl(self, traced):
        with trace.start_span("t.root", attrs={"k": 1}) as root:
            with trace.start_span("t.child") as child:
                assert child.trace_id == root.trace_id
                assert child.parent_id == root.span_id
        spans = read(traced)
        (r,) = by_name(spans, "t.root")
        (c,) = by_name(spans, "t.child")
        assert c["parent"] == r["span"]
        assert c["trace"] == r["trace"]
        assert r["parent"] == ""
        assert r["attrs"] == {"k": 1}
        assert r["dur_ms"] >= c["dur_ms"] >= 0
        assert r["pid"] == os.getpid()

    def test_exception_recorded_and_propagated(self, traced):
        with pytest.raises(ValueError):
            with trace.start_span("t.boom"):
                raise ValueError("payload")
        (s,) = by_name(read(traced), "t.boom")
        assert "ValueError: payload" in s["error"]

    def test_traceparent_roundtrip_and_malformed(self):
        trace_id, span_id = "ab" * 16, "cd" * 8
        tp = trace.format_traceparent(trace_id, span_id)
        assert trace.parse_traceparent(tp) == (trace_id, span_id)
        for bad in (
            "", None, "00-short-cd-01", "garbage",
            "00-" + "zz" * 16 + "-" + "cd" * 8 + "-01",
            "00-" + "ab" * 16 + "-" + "cd" * 8,
        ):
            assert trace.parse_traceparent(bad) is None

    def test_explicit_parent_adopts_remote_trace(self, traced):
        remote = trace.format_traceparent("12" * 16, "34" * 8)
        with trace.start_span("t.adopted", parent=remote):
            pass
        (s,) = by_name(read(traced), "t.adopted")
        assert s["trace"] == "12" * 16
        assert s["parent"] == "34" * 8

    def test_garbled_parent_degrades_to_fresh_trace(self, traced):
        with trace.start_span("t.fresh", parent="not-a-traceparent"):
            pass
        (s,) = by_name(read(traced), "t.fresh")
        assert s["parent"] == ""
        assert len(s["trace"]) == 32

    def test_record_span_parents_on_active_span(self, traced):
        with trace.start_span("t.op") as op:
            trace.record_span("t.retro", time.time(), 0.001, attrs={"n": 2})
        spans = read(traced)
        (retro,) = by_name(spans, "t.retro")
        assert retro["parent"] == op.span_id
        assert retro["trace"] == op.trace_id
        assert retro["attrs"] == {"n": 2}

    def test_current_traceparent_inside_and_outside(self, traced):
        assert trace.current_traceparent() == ""
        with trace.start_span("t.active") as s:
            assert trace.current_traceparent() == s.traceparent
        assert trace.current_traceparent() == ""


class TestDisabledFastPath:
    def test_shared_noop_no_allocation_no_file(self, tmp_path, monkeypatch):
        monkeypatch.delenv(trace.ENV_TRACE, raising=False)
        log = tmp_path / "never.jsonl"
        monkeypatch.setenv(trace.ENV_TRACE_LOG, str(log))
        trace.reset_for_tests()
        # ONE shared object: the disabled path allocates nothing per call.
        a = trace.start_span("t.a")
        b = trace.start_span("t.b", attrs={"x": 1})
        assert a is b is trace.NOOP_SPAN
        with a as s:
            s.set_attr("ignored", True)
            assert s.traceparent == ""
            with trace.start_span("t.nested"):
                pass
        trace.record_span("t.retro", time.time(), 0.1)
        assert trace.current_traceparent() == ""
        assert not log.exists()
        assert trace.recent_spans() == []


class TestFlightRecorder:
    def test_ring_bounded_newest_first(self, traced, monkeypatch):
        monkeypatch.setenv(trace.ENV_TRACE_RING, "4")
        trace.reset_for_tests()  # ring size is read at first record
        for i in range(7):
            with trace.start_span("t.ring", attrs={"i": i}):
                pass
        recent = trace.recent_spans()
        assert len(recent) == 4
        assert [s["attrs"]["i"] for s in recent] == [6, 5, 4, 3]
        assert trace.recent_spans(2) == recent[:2]

    def test_unwritable_log_drops_spans_never_raises(
        self, tmp_path, monkeypatch, caplog
    ):
        """The observability layer must never take down the bind path: a
        trace log pointing at a missing directory drops batches with one
        warning, and the in-memory ring keeps recording."""
        monkeypatch.setenv(trace.ENV_TRACE, "1")
        monkeypatch.setenv(
            trace.ENV_TRACE_LOG, str(tmp_path / "no-such-dir" / "t.jsonl")
        )
        trace.reset_for_tests()
        try:
            with trace.start_span("t.dropped"):
                pass
            trace.flush()  # forces a write attempt — must not raise
            assert [s["name"] for s in trace.recent_spans()] == ["t.dropped"]
        finally:
            trace.reset_for_tests()

    def test_non_json_attr_degrades_to_repr(self, traced):
        """A set (or any non-JSON value) in span attrs must not poison
        the batch or escape into the traced bind path — it serializes as
        its repr and every other record survives."""
        with trace.start_span("t.bad-attr") as s:
            s.set_attr("nodes", {"n1"})
        with trace.start_span("t.good"):
            pass
        spans = read(traced)
        assert {s["name"] for s in spans} == {"t.bad-attr", "t.good"}
        (bad,) = by_name(spans, "t.bad-attr")
        assert bad["attrs"]["nodes"] == repr({"n1"})

    def test_torn_log_line_is_skipped(self, traced):
        with trace.start_span("t.keep"):
            pass
        trace.flush()
        with open(traced, "a") as f:
            f.write('{"t": "span", "trace": "x", "span"')  # torn tail
        spans = trace.read_log(traced)
        assert [s["name"] for s in spans] == ["t.keep"]


# ------------------------------------------------------- propagation edges


class TestGrpcPropagation:
    def test_metadata_roundtrip_through_real_sockets(self, traced, tmp_path):
        """Client span → gRPC metadata → server rpc span → plugin spans:
        ONE trace across the kubelet wire boundary, with the client-side
        span as the RPC span's parent."""
        from tests.test_device_state import mk_claim
        from tests.test_driver import mk_driver
        from tpudra.plugin.grpcserver import DRAClient

        kube = FakeKube()
        d = mk_driver(tmp_path / "plugin", kube)
        d.start()
        client = DRAClient(d.sockets.dra_socket_path)
        try:
            claim = mk_claim("tr-1", ["tpu-0"], name="tr-1")
            kube.create(gvr.RESOURCE_CLAIMS, claim, "default")
            with trace.start_span("test.kubelet") as kubelet_span:
                resp = client.prepare([claim])
                assert "error" not in resp["claims"]["tr-1"]
                client.unprepare([claim])
        finally:
            client.close()
            d.stop()
        spans = read(traced)
        (rpc,) = by_name(spans, "rpc.NodePrepareResources")
        assert rpc["trace"] == kubelet_span.trace_id
        assert rpc["parent"] == kubelet_span.span_id
        # The plugin's phase spans chain under the RPC span in-process.
        (prep,) = by_name(spans, "plugin.prepare")
        assert prep["trace"] == kubelet_span.trace_id
        assert prep["parent"] == rpc["span"]
        phase_names = {
            s["name"] for s in spans if s["trace"] == kubelet_span.trace_id
        }
        assert {
            "bind.rmw-begin", "bind.effects", "bind.rmw-finish",
            "bind.cdi-write", "checkpoint.commit", "checkpoint.fsync",
        } <= phase_names

    def test_untraced_client_sends_no_metadata(self, tmp_path, monkeypatch):
        """Disabled tracing: no metadata key on the wire, no spans, and
        the RPC still works — the production-default path."""
        monkeypatch.delenv(trace.ENV_TRACE, raising=False)
        trace.reset_for_tests()
        from tests.test_device_state import mk_claim
        from tests.test_driver import mk_driver
        from tpudra.plugin.grpcserver import DRAClient

        kube = FakeKube()
        d = mk_driver(tmp_path / "plugin", kube)
        d.start()
        client = DRAClient(d.sockets.dra_socket_path)
        try:
            claim = mk_claim("tr-2", ["tpu-0"], name="tr-2")
            kube.create(gvr.RESOURCE_CLAIMS, claim, "default")
            resp = client.prepare([claim])
            assert "error" not in resp["claims"]["tr-2"]
            client.unprepare([claim])
        finally:
            client.close()
            d.stop()
        assert trace.recent_spans() == []


class TestWalPropagation:
    def test_claim_record_journals_traceparent(self, traced, tmp_path):
        """The WAL edge, plugin side: a traced bind journals its
        traceparent on the claim record; an untraced bind journals None
        (byte-identical checkpoints to pre-trace drivers)."""
        from tests.test_device_state import mk_claim
        from tests.test_driver import mk_driver

        kube = FakeKube()
        d = mk_driver(tmp_path / "plugin", kube)
        claim = mk_claim("tp-1", ["tpu-0"], name="tp-1")
        with trace.start_span("test.bind") as s:
            d.prepare_resource_claims([claim])
        rec = d.state._cp.read().prepared_claims["tp-1"]
        parsed = trace.parse_traceparent(rec.traceparent)
        assert parsed is not None and parsed[0] == s.trace_id
        d.unprepare_resource_claims([{"uid": "tp-1"}])
        # Untraced arm: the field stays None (serde drops it entirely).
        os.environ.pop(trace.ENV_TRACE, None)
        claim2 = mk_claim("tp-2", ["tpu-1"], name="tp-2")
        d.prepare_resource_claims([claim2])
        assert d.state._cp.read().prepared_claims["tp-2"].traceparent is None
        d._checkpoints.close()

    def test_gang_recovery_resumes_original_trace(self, traced, tmp_path):
        """The WAL edge across a CRASH (riding the existing
        mid-gang-reserve sweep point): a fresh manager's recover() emits
        its spans into the trace journaled at reserve time."""
        from tests.test_gang import (
            RecordingBinder,
            mk_claims,
            mk_members,
        )
        from tpudra.controller.gang import GangReservationManager
        from tpudra.plugin import checkpoint as checkpoint_mod
        from tpudra.plugin.checkpoint import CheckpointManager, SimulatedCrash

        members = mk_members(3)
        claims = mk_claims(members)
        binder = RecordingBinder()
        cp = CheckpointManager(str(tmp_path / "gangs"))
        mgr = GangReservationManager(cp, binder)
        with trace.start_span("test.reserve") as reserve_span:
            with checkpoint_mod.armed_crash("mid-gang-reserve"):
                with pytest.raises(SimulatedCrash):
                    mgr.reserve("tg", members, claims)
        cp.abandon()
        assert binder.bound  # the partial gang the crash left

        cp2 = CheckpointManager(str(tmp_path / "gangs"))
        mgr2 = GangReservationManager(cp2, binder)
        rec = mgr2.gangs()["tg"]
        assert trace.parse_traceparent(rec.traceparent) is not None
        assert rec.traceparent.split("-")[1] == reserve_span.trace_id
        assert mgr2.recover() == ["tg"]
        assert not binder.bound
        cp2.close()
        spans = read(traced)
        (recover,) = by_name(spans, "gang.recover")
        # The recovery span landed in the ORIGINAL reserve trace.
        assert recover["trace"] == reserve_span.trace_id
        assert recover["attrs"]["gang"] == "tg"


class TestGrantEnvPropagation:
    def test_rank_process_emits_child_span_from_grant_env(
        self, traced, tmp_path
    ):
        """The process-boundary edge: a stand-in rank, handed ONLY the
        claim's CDI grant env, emits a span that chains into the bind's
        trace in the shared log."""
        from tests.test_gang import _cd_stack, _gang_inputs
        from tpudra.controller.gang import GangReservationManager
        from tpudra.plugin.checkpoint import CheckpointManager
        from tpudra.sim.multihost import DriverGangBinder

        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            # The rank stand-in and grant-env parsing are trace_report's
            # (the make trace-check body) — one copy of the contract.
            from trace_report import _RANK_SNIPPET, _grant_env
        finally:
            sys.path.pop(0)

        kube, nodes, drivers = _cd_stack(tmp_path, n=2)
        members, claims = _gang_inputs(kube, nodes)
        cp = CheckpointManager(str(tmp_path / "gangs"))
        mgr = GangReservationManager(cp, DriverGangBinder(drivers))
        mgr.reserve("tg-env", members, claims)
        member = members[0]
        env = _grant_env(drivers[member.node], member.claim_uid)
        tp = env[trace.TRACEPARENT_ENV]
        assert trace.parse_traceparent(tp) is not None
        proc = subprocess.run(
            [sys.executable, "-c", _RANK_SNIPPET],
            env={
                trace.ENV_TRACE: "1",
                trace.ENV_TRACE_LOG: traced,
                trace.TRACEPARENT_ENV: tp,
                "PYTHONPATH": REPO,
                "PATH": os.environ.get("PATH", ""),
            },
            capture_output=True,
            text=True,
            timeout=60,
        )
        assert proc.returncode == 0, proc.stderr
        mgr.release("tg-env")
        cp.close()
        for d in drivers.values():
            d._checkpoints.close()
        spans = read(traced)
        (rank,) = by_name(spans, "rank.worker")
        (reserve,) = by_name(spans, "gang.reserve")
        assert rank["trace"] == reserve["trace"]
        assert rank["pid"] != reserve["pid"]
        # The rank's parent is a span of the member bind's subtree.
        binds = by_name(spans, "gang.bind-member")
        spans_by_id = {s["span"]: s for s in spans}
        node = spans_by_id[rank["parent"]]
        while node["name"] != "gang.bind-member":
            node = spans_by_id[node["parent"]]
        assert node["span"] in {b["span"] for b in binds}

    def test_claimenv_parses_traceparent(self):
        from tpudra.workload.envspec import ClaimEnv

        env = ClaimEnv.from_environ({"TPUDRA_TRACEPARENT": "00-x-y-01"})
        assert env.traceparent == "00-x-y-01"
        assert ClaimEnv.from_environ({}).traceparent == ""


# ------------------------------------------------------------ trace_report


class TestTraceReport:
    def _report_mod(self):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            import trace_report

            return trace_report
        finally:
            sys.path.pop(0)

    def test_critical_path_picks_latest_ending_chain(self, traced):
        tr = self._report_mod()
        with trace.start_span("t.root"):
            with trace.start_span("t.fast"):
                pass
            with trace.start_span("t.slow"):
                time.sleep(0.02)
        traces = tr.build_traces(read(traced))
        (t,) = traces.values()
        (root,) = t["roots"]
        path = [s["name"] for s in tr.critical_path(root, t["children"])]
        assert path == ["t.root", "t.slow"]
        summary = tr.critical_path_summary(root, t["children"])
        assert summary[0]["pct"] == 100.0

    def test_report_renders_and_phase_means(self, traced):
        tr = self._report_mod()
        with trace.start_span("t.root"):
            with trace.start_span("t.phase"):
                pass
        trace.flush()  # same-process reader (the flush-cadence contract)
        text = tr.report(traced)
        assert "t.root" in text and "critical path" in text
        means = tr.phase_means(read(traced), "t.root")
        assert set(means) == {"t.root", "t.phase"}
        assert means["t.phase"]["n"] == 1

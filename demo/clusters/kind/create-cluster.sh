#!/usr/bin/env bash
# Bring up a DRA-enabled kind cluster, build + load the driver image, and
# install the chart with the mock device backend
# (reference demo/clusters/kind/create-cluster.sh).
set -euo pipefail

HERE="$(cd "$(dirname "$0")" && pwd)"
REPO="$(cd "$HERE/../../.." && pwd)"
CLUSTER_NAME="${CLUSTER_NAME:-tpudra}"
IMAGE="${IMAGE:-tpudra:dev}"

command -v kind >/dev/null || { echo "kind not found (https://kind.sigs.k8s.io)"; exit 1; }
command -v kubectl >/dev/null || { echo "kubectl not found"; exit 1; }
command -v helm >/dev/null || { echo "helm not found"; exit 1; }
command -v docker >/dev/null || { echo "docker not found"; exit 1; }

echo "==> creating kind cluster ${CLUSTER_NAME}"
kind create cluster --name "${CLUSTER_NAME}" \
  --config "${HERE}/kind-cluster-config.yaml" --wait 120s

echo "==> building driver image ${IMAGE}"
docker build -f "${REPO}/deployments/container/Dockerfile" -t "${IMAGE}" "${REPO}"

echo "==> building workload image (driver runtime + jax, for demo pods)"
docker build -f "${REPO}/deployments/container/Dockerfile" --target workload   -t tpudra-workload:latest "${REPO}"

echo "==> loading images into kind"
kind load docker-image --name "${CLUSTER_NAME}" "${IMAGE}" tpudra-workload:latest

echo "==> installing chart (mock device backend)"
"${HERE}/install-driver.sh"

echo "==> done; try: kubectl apply -f ${REPO}/demo/specs/tpu-test1.yaml"

#!/usr/bin/env bash
# Install (or upgrade) the chart into the current kubectl context with the
# mock device backend — suitable for kind/CI clusters without TPUs
# (reference demo/clusters/kind/install-dra-driver-gpu.sh).
set -euo pipefail

HERE="$(cd "$(dirname "$0")" && pwd)"
REPO="$(cd "$HERE/../../.." && pwd)"
IMAGE="${IMAGE:-tpudra:dev}"
NAMESPACE="${NAMESPACE:-tpudra-system}"

helm upgrade --install tpudra "${REPO}/deployments/helm/tpu-dra-driver" \
  --namespace "${NAMESPACE}" --create-namespace \
  --set image.repository="${IMAGE%:*}" \
  --set image.tag="${IMAGE##*:}" \
  --set kubeletPlugin.deviceBackend=mock \
  --wait --timeout 5m

kubectl -n "${NAMESPACE}" get pods

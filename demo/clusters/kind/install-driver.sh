#!/usr/bin/env bash
# Install (or upgrade) the chart into the current kubectl context with the
# mock device backend — suitable for kind/CI clusters without TPUs
# (reference demo/clusters/kind/install-dra-driver-gpu.sh).
set -euo pipefail

HERE="$(cd "$(dirname "$0")" && pwd)"
REPO="$(cd "$HERE/../../.." && pwd)"
IMAGE="${IMAGE:-tpudra:dev}"
NAMESPACE="${NAMESPACE:-tpudra-system}"

# Split "<repo>[:tag]" on the LAST colon only when that colon belongs to a
# tag (i.e. appears after the final slash) — registries carry ports
# (localhost:5001/tpudra) and tags are optional.
if [[ "${IMAGE##*/}" == *:* ]]; then
  IMAGE_REPO="${IMAGE%:*}"
  IMAGE_TAG="${IMAGE##*:}"
else
  IMAGE_REPO="${IMAGE}"
  IMAGE_TAG="latest"
fi

helm upgrade --install tpudra "${REPO}/deployments/helm/tpu-dra-driver" \
  --namespace "${NAMESPACE}" --create-namespace \
  --set image.repository="${IMAGE_REPO}" \
  --set image.tag="${IMAGE_TAG}" \
  --set kubeletPlugin.deviceBackend=mock \
  --wait --timeout 5m

kubectl -n "${NAMESPACE}" get pods

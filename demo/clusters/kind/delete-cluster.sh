#!/usr/bin/env bash
# Tear the demo cluster down (reference demo/clusters/kind/delete-cluster.sh).
set -euo pipefail
kind delete cluster --name "${CLUSTER_NAME:-tpudra}"

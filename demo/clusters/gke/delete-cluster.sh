#!/usr/bin/env bash
# Tear down the GKE demo cluster (reference demo/clusters/gke/delete-cluster.sh).
set -euo pipefail

: "${PROJECT_NAME:=$(gcloud config list --format 'value(core.project)' 2>/dev/null)}"
CLUSTER_NAME="${CLUSTER_NAME:-tpudra-cluster}"
ZONE="${ZONE:-us-central2-b}"

gcloud container clusters delete "${CLUSTER_NAME}" \
  --quiet --project="${PROJECT_NAME}" --zone="${ZONE}"

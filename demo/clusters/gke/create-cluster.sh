#!/usr/bin/env bash
# Bring up a GKE cluster with a TPU slice node pool and the DRA APIs enabled
# (reference demo/clusters/gke/create-cluster.sh, retargeted from A100 VMs
# to a Cloud TPU node pool — GKE is where multi-host TPU slices live).
#
# Environment knobs (all optional):
#   CLUSTER_NAME   cluster name                    (default: tpudra-cluster)
#   REGION / ZONE  location                        (default: us-central2-b,
#                  a zone with v5e capacity)
#   CLUSTER_VERSION GKE minor with DRA beta        (default: 1.34)
#   TPU_MACHINE    TPU VM machine type             (default: ct5lp-hightpu-4t,
#                  one v5e host with 4 chips)
#   TPU_TOPOLOGY   slice topology                  (default: 2x4 — a 2-host
#                  slice, the smallest multi-host ComputeDomain)
#   NUM_HOSTS      hosts in the slice node pool    (default: 2, must match
#                  the topology's host count)
set -euo pipefail

: "${PROJECT_NAME:=$(gcloud config list --format 'value(core.project)' 2>/dev/null)}"
if [[ -z "${PROJECT_NAME}" ]]; then
  echo "Project name could not be determined; run 'gcloud config set project'"
  exit 1
fi

CLUSTER_NAME="${CLUSTER_NAME:-tpudra-cluster}"
ZONE="${ZONE:-us-central2-b}"
CLUSTER_VERSION="${CLUSTER_VERSION:-1.34}"
TPU_MACHINE="${TPU_MACHINE:-ct5lp-hightpu-4t}"
TPU_TOPOLOGY="${TPU_TOPOLOGY:-2x4}"
NUM_HOSTS="${NUM_HOSTS:-2}"

echo "==> creating GKE cluster ${CLUSTER_NAME} (${ZONE}, ${CLUSTER_VERSION})"
# DRA needs the resource.k8s.io API group; on GKE that is gated behind
# --enable-kubernetes-unstable-apis until it reaches GA in the channel.
gcloud container clusters create "${CLUSTER_NAME}" \
  --quiet \
  --project="${PROJECT_NAME}" \
  --zone="${ZONE}" \
  --cluster-version="${CLUSTER_VERSION}" \
  --num-nodes=1 \
  --enable-kubernetes-unstable-apis=resource.k8s.io/v1beta1/deviceclasses,resource.k8s.io/v1beta1/resourceclaims,resource.k8s.io/v1beta1/resourceclaimtemplates,resource.k8s.io/v1beta1/resourceslices

echo "==> adding TPU slice node pool (${TPU_MACHINE}, topology ${TPU_TOPOLOGY})"
# A multi-host slice node pool: GKE provisions NUM_HOSTS TPU VMs forming one
# ICI-connected slice. The driver's ComputeDomain machinery maps 1:1 onto
# it (clique = slice, host index = TPU_WORKER_ID).
gcloud container node-pools create tpu-slice \
  --quiet \
  --project="${PROJECT_NAME}" \
  --zone="${ZONE}" \
  --cluster="${CLUSTER_NAME}" \
  --machine-type="${TPU_MACHINE}" \
  --tpu-topology="${TPU_TOPOLOGY}" \
  --num-nodes="${NUM_HOSTS}" \
  --node-labels=tpudra.google.com/enabled=true

gcloud container clusters get-credentials "${CLUSTER_NAME}" \
  --project="${PROJECT_NAME}" --zone="${ZONE}"

echo "==> done; install the driver with:"
echo "    IMAGE=<your-registry>/tpudra:TAG demo/clusters/gke/install-driver.sh"

#!/usr/bin/env bash
# Install the driver chart on a GKE TPU cluster with the *native* device
# backend (reference demo/clusters/gke/install-dra-driver-gpu.sh).  Unlike
# the kind path this expects real /dev/accel* devices on the TPU node pool,
# so the kubelet plugin runs with --device-backend=native (libtpuinfo reads
# sysfs PCI + the Cloud TPU VM metadata env).
set -euo pipefail

HERE="$(cd "$(dirname "$0")" && pwd)"
REPO="$(cd "$HERE/../../.." && pwd)"
IMAGE="${IMAGE:?set IMAGE=<registry>/tpudra:<tag> (pushed where GKE can pull)}"
NAMESPACE="${NAMESPACE:-tpudra-system}"

if [[ "${IMAGE##*/}" == *:* ]]; then
  IMAGE_REPO="${IMAGE%:*}"; IMAGE_TAG="${IMAGE##*:}"
else
  IMAGE_REPO="${IMAGE}"; IMAGE_TAG="latest"
fi

helm upgrade --install tpudra "${REPO}/deployments/helm/tpu-dra-driver" \
  --namespace "${NAMESPACE}" --create-namespace \
  --set image.repository="${IMAGE_REPO}" \
  --set image.tag="${IMAGE_TAG}" \
  --set kubeletPlugin.deviceBackend=native \
  --set kubeletPlugin.nodeSelector."tpudra\.google\.com/enabled"=\"true\" \
  --wait --timeout 10m

kubectl -n "${NAMESPACE}" get pods -o wide
echo "==> try: kubectl apply -f ${REPO}/demo/specs/tpu-test1.yaml"
echo "==> multi-host slice: kubectl apply -f ${REPO}/demo/specs/tpu-test-cd.yaml"

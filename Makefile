# Developer entry points (the reference's Makefile/versions.mk analog).

# tier1 needs bash (pipefail / PIPESTATUS); everything else is fine under it.
SHELL := /bin/bash

IMAGE ?= tpudra:dev
VERSION ?= $(shell grep -m1 '__version__' tpudra/__init__.py | cut -d'"' -f2)
GIT_COMMIT ?= $(shell git rev-parse --short HEAD 2>/dev/null || echo unknown)

.PHONY: all native test test-fast lint lockgraph lockgraph-docs effectgraph effectgraph-docs racegraph racegraph-docs trace-check tier1 bats bats-real bench bench-bind bench-apiserver bench-checkpoint bench-cluster bench-gang bench-trace bench-storage bench-partition bench-failover e2e-multihost soak image helm-render clean

all: native test

# Static analysis gate: tpudra-lint + tpudra-lockgraph + tpudra-effectgraph
# + tpudra-racegraph (one stdlib AST analyzer sharing one parse pass and
# one call graph, docs/static-analysis.md) plus ruff/mypy when installed.
# Nonzero exit on any finding.
lint:
	bash hack/lint.sh

# Just the whole-program lock rules (LOCK-CYCLE, BLOCK-UNDER-LOCK-IP,
# FLOCK-INVERSION) — the quick loop while reworking concurrency.  Also part
# of `make lint`/`make tier1` (hack/lint.sh runs the full analyzer), and
# gated in-suite by tests/test_lockgraph.py::test_lockgraph_is_clean.
lockgraph:
	python -m tpudra.analysis --lockgraph

# Regenerate the checked-in acquisition-order doc from the static model
# (tests/test_lockgraph.py::test_lock_order_doc_is_fresh diffs it).
lockgraph-docs:
	python -m tpudra.analysis --emit-dot docs/lock-order.md

# Just the whole-program WAL rules (WAL-INTENT-BEFORE-EFFECT,
# WAL-RECOVERY-EXHAUSTIVE, FENCE-DOMINATES-COMMIT, STRIPE-ORDER) — the
# quick loop while reworking the checkpoint/bind path.  Also part of
# `make lint`/`make tier1` (hack/lint.sh runs the full analyzer), and
# gated in-suite by tests/test_effectgraph.py::test_effectgraph_is_clean.
effectgraph:
	python -m tpudra.analysis --effectgraph

# Regenerate the checked-in effect-graph doc from the static WAL model
# (tests/test_effectgraph.py::test_effect_graph_doc_is_fresh diffs it).
effectgraph-docs:
	python -m tpudra.analysis --emit-effectgraph docs/effect-graph.md

# Just the whole-program race rules (RACE, GUARD-CONSISTENCY,
# THREAD-CONFINED-ESCAPE) — the quick loop while reworking shared state.
# Also part of `make lint`/`make tier1` (hack/lint.sh runs the full
# analyzer), and gated in-suite by
# tests/test_racegraph.py::test_racegraph_is_clean.
racegraph:
	python -m tpudra.analysis --racegraph

# Regenerate the checked-in race-model doc from the static thread/race
# model (tests/test_racegraph.py::test_race_model_doc_is_fresh diffs it).
racegraph-docs:
	python -m tpudra.analysis --emit-racegraph docs/race-model.md

native:
	$(MAKE) -C native

# slow-marked lanes (the chaos soak wrapper) have their own entry points
# (`make soak`, `pytest -m slow`) — neither dev loop should pay them.
test: native
	python -m pytest tests/ -q -m 'not slow'

# The quick loop: skip the slower e2e/stress/native suites.
test-fast:
	python -m pytest tests/ -q -m 'not slow' \
	  --ignore=tests/test_e2e.py \
	  --ignore=tests/test_computedomain.py \
	  --ignore=tests/test_native.py

# Trace propagation gate (docs/tracing.md): a traced mini-bench — gang
# reservation through real CD drivers + one stand-in rank process per
# member — asserted to yield a COMPLETE root→rank span tree through
# tools/trace_report.py.  Seconds of wall time, no jax; part of the
# tier-1 prerequisite chain so a broken propagation edge fails fast.
trace-check:
	env JAX_PLATFORMS=cpu python tools/trace_report.py --self-check

# The exact ROADMAP.md tier-1 verify command (what the PR driver runs),
# with the lint gate first: an invariant violation fails fast, before ~15
# minutes of tests.  (The raw pytest command also gates via
# tests/test_lint.py::test_repo_is_clean.)
tier1: lint trace-check
	set -o pipefail; rm -f /tmp/_t1.log; \
	timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
	  -m 'not slow' --continue-on-collection-errors \
	  -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 \
	  | tee /tmp/_t1.log; rc=$${PIPESTATUS[0]}; \
	echo DOTS_PASSED=$$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$$' /tmp/_t1.log | tr -cd . | wc -c); \
	exit $$rc

# Whole e2e suite under minibats (fast runner).
bats: native
	for f in tests/bats/test_*.bats; do \
	  echo "== $$f"; bash tests/bats/minibats.sh $$f || exit 1; done

# Real-bats-semantics lane (tests/bats/vendor/rbats): bats-core's process
# model — fresh process per test, exported-env-only state from setup_file.
# File list shared with tests/test_bats.py via vendor/lane-files.txt.
bats-real: native
	bash tests/bats/vendor/rbats \
	  tests/bats/vendor/selftest/semantics.bats \
	  $$(grep -v '^#' tests/bats/vendor/lane-files.txt | sed 's|^|tests/bats/|')

# Full bench; afterwards print the bind-p50 delta vs the newest prior-round
# BENCH_r*.json (when one exists with a parsed headline).
bench: native
	set -o pipefail; python bench.py | tee /tmp/tpudra_bench_out.txt
	python tools/bench_delta.py /tmp/tpudra_bench_out.txt

# CPU-only bind sections (headline + multi-claim batch) — the quick A/B
# artifact for bind-path changes.
bench-bind:
	set -o pipefail; python bench.py --bind-only | tee /tmp/tpudra_bench_out.txt
	python tools/bench_delta.py /tmp/tpudra_bench_out.txt

# The apiserver-RTT A/B in one command: bind sections plus the batch bind
# at an injected 10 ms per-request RTT, watch-cached claim resolution
# interleaved against per-claim GETs (docs/bind-path.md "Claim resolution
# and slice publication").
APISERVER_LATENCY_MS ?= 10
bench-apiserver:
	set -o pipefail; python bench.py --bind-only \
	  --apiserver-latency-ms $(APISERVER_LATENCY_MS) \
	  | tee /tmp/tpudra_bench_out.txt
	python tools/bench_delta.py /tmp/tpudra_bench_out.txt

# Checkpoint-storage churn A/B (docs/bind-path.md "Checkpoint storage"):
# N resident claims x M status-flip mutates, interleaved WAL-vs-snapshot
# arms, plus the 8-way group-commit fsync count (medians of 3 waves).
bench-checkpoint:
	set -o pipefail; python bench.py --checkpoint-churn \
	  | tee /tmp/tpudra_bench_out.txt
	python tools/bench_delta.py /tmp/tpudra_bench_out.txt

# Cluster-scale control-plane A/B (docs/cluster-scale.md): N simulated
# nodes + one controller under seeded churn, fixed arm (serialize-once
# fan-out, fair queue, bulk publication) interleaved against the legacy
# arm.  CLUSTER_NODES sweeps node counts; CPU-only.  Wall time is bound
# by the box's thread/syscall cost, not the harness: minutes on a
# developer machine, hours for the full sweep on a 2-core sandboxed CI
# box (run one node count at a time there: CLUSTER_NODES=256).
CLUSTER_NODES ?= 8,128,256
bench-cluster:
	set -o pipefail; python bench.py --cluster-scale \
	  --nodes $(CLUSTER_NODES) \
	  | tee /tmp/tpudra_bench_out.txt
	python tools/bench_delta.py /tmp/tpudra_bench_out.txt

# Multi-host e2e (docs/multi-host.md): gang-reserve a ComputeDomain claim
# for a 4-node slice, launch one real OS process per node, run a
# cross-process jax.distributed psum, and prove the kill-one-rank case
# rolls back to zero bound claims — plus the gang crash sweep
# (mid-gang-reserve / mid-gang-rollback, tests/test_gang.py).
e2e-multihost:
	env JAX_PLATFORMS=cpu python -m pytest -q -m multihost tests/test_multihost.py
	env JAX_PLATFORMS=cpu python -m pytest -q tests/test_gang.py

# Gang-bind latency A/B (docs/multi-host.md): p50/p99 for 2/4/8-node
# slices with interleaved bound-vs-rollback arms, through real CD plugin
# drivers; CPU-only.
bench-gang:
	set -o pipefail; python bench.py --gang | tee /tmp/tpudra_bench_out.txt
	python tools/bench_delta.py /tmp/tpudra_bench_out.txt

# Tracing-overhead A/B (docs/tracing.md): the single-claim bind with
# TPUDRA_TRACE=1 interleaved against disabled, plus the span critical
# path from the traced arm's log — the ≤5% overhead gate, and the phase
# attribution future bind-path PRs cite alongside their p50 deltas.
bench-trace:
	set -o pipefail; python bench.py --trace-ab | tee /tmp/tpudra_bench_out.txt
	python tools/bench_delta.py /tmp/tpudra_bench_out.txt

# Degraded-mode shed A/B (docs/bind-path.md "Storage fault contract"):
# healthy bind p50 vs the fail-fast typed-error shed path with the
# checkpoint dir ENOSPC-faulted through the storage seam, plus heal
# convergence — the bounded-p99 acceptance arm for storage-fault PRs.
bench-storage:
	set -o pipefail; python bench.py --storage-degraded | tee /tmp/tpudra_bench_out.txt
	python tools/bench_delta.py /tmp/tpudra_bench_out.txt

# Controller-failover A/B (docs/ha.md): time-to-new-leader p50/p99
# across crash-shaped and graceful lease handoffs, plus bind p99 during
# a 429 storm vs quiet (interleaved arms); CPU-only.
bench-failover:
	set -o pipefail; python bench.py --failover | tee /tmp/tpudra_bench_out.txt
	python tools/bench_delta.py /tmp/tpudra_bench_out.txt

# Fractional-chip A/B (docs/partitioning.md): interleaved
# partitioned-vs-whole-chip bind p50/p99 through the real bind path
# (partition create + per-partition WAL records), plus the
# packing-efficiency scenario (N half-chip claims per chip vs whole-chip
# claims — resident claims and claims placed per chip-hour); CPU-only.
bench-partition:
	set -o pipefail; python bench.py --partition | tee /tmp/tpudra_bench_out.txt
	python tools/bench_delta.py /tmp/tpudra_bench_out.txt

# Chaos soak (docs/chaos.md): compound-fault long-run — apiserver latency
# spikes + forced watch closes + kubelet restarts + SIGKILL-equivalent
# plugin crashes at random checkpoint boundaries + torn WAL tails + GC
# clock skew — against the cluster sim, with invariants asserted
# CONTINUOUSLY and a JSON SLO report as the exit gate.  The short profile
# is seeded and ≤ 120 s wall for ≥ 1 simulated hour of churn; the lock
# witness is armed and merged at finalize.  Not tier-1 (wall-time cost);
# `pytest -m slow` runs the same profile via tests/test_soak.py.
SOAK_SEED ?= 42
SOAK_REPORT ?= /tmp/tpudra_soak.json
soak:
	python -m tpudra.sim.chaos --profile short --seed $(SOAK_SEED) \
	  --report $(SOAK_REPORT)
	python tools/soak_report.py $(SOAK_REPORT) --assert-slo

image:
	docker build -f deployments/container/Dockerfile \
	  --build-arg VERSION=$(VERSION) --build-arg GIT_COMMIT=$(GIT_COMMIT) \
	  -t $(IMAGE) .

helm-render:
	python tools/helmlite.py deployments/helm/tpu-dra-driver

clean:
	rm -rf native/build

# Developer entry points (the reference's Makefile/versions.mk analog).

IMAGE ?= tpudra:dev
VERSION ?= $(shell grep -m1 '__version__' tpudra/__init__.py | cut -d'"' -f2)
GIT_COMMIT ?= $(shell git rev-parse --short HEAD 2>/dev/null || echo unknown)

.PHONY: all native test test-fast bats bats-real bench image helm-render clean

all: native test

native:
	$(MAKE) -C native

test: native
	python -m pytest tests/ -q

# The quick loop: skip the slower e2e/stress/native suites.
test-fast:
	python -m pytest tests/ -q \
	  --ignore=tests/test_e2e.py \
	  --ignore=tests/test_computedomain.py \
	  --ignore=tests/test_native.py

# Whole e2e suite under minibats (fast runner).
bats: native
	for f in tests/bats/test_*.bats; do \
	  echo "== $$f"; bash tests/bats/minibats.sh $$f || exit 1; done

# Real-bats-semantics lane (tests/bats/vendor/rbats): bats-core's process
# model — fresh process per test, exported-env-only state from setup_file.
# File list shared with tests/test_bats.py via vendor/lane-files.txt.
bats-real: native
	bash tests/bats/vendor/rbats \
	  tests/bats/vendor/selftest/semantics.bats \
	  $$(grep -v '^#' tests/bats/vendor/lane-files.txt | sed 's|^|tests/bats/|')

bench: native
	python bench.py

image:
	docker build -f deployments/container/Dockerfile \
	  --build-arg VERSION=$(VERSION) --build-arg GIT_COMMIT=$(GIT_COMMIT) \
	  -t $(IMAGE) .

helm-render:
	python tools/helmlite.py deployments/helm/tpu-dra-driver

clean:
	rm -rf native/build
